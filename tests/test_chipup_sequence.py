"""End-to-end CPU rehearsal of the chip-up capture sequence.

The TPU window is scarce (17 minutes in round 4); the one thing that must
not fail during it is the chipup.py pass plumbing.  These tests drive the
real pass functions — real subprocesses, real artifact writes, real merge
policy — with JAX forced to CPU (bench.py's '--worker tpu' degrades to
the CPU smoke instead of hanging on axon init) and artifacts redirected
to a tmp dir via CHIPUP_ARTIFACT_DIR.

What they pin down:
- the banking pass writes a flagged snapshot even when the row is
  not-good (CPU smoke: mfu None) — flagged evidence beats none;
- the merge policy then REFUSES to let a second not-good row replace
  nothing-better, and lets a fabricated good row replace the flagged one;
- the kernels pass installs the selfcheck artifact on exit 0;
- the LM pass rejects tiny-smoke rows (a CPU smoke must never become
  LM evidence).
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.slow


def _drive(tmp_path, src, extra_env=None, timeout=900):
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               CHIPUP_ARTIFACT_DIR=str(tmp_path),
               CHIPUP_ATTEMPTS=str(tmp_path / "attempts.jsonl"),
               CHIPUP_LOCK=str(tmp_path / "lock"),
               CHIPUP_STRAY_SWEEP="0",
               **(extra_env or {}))
    r = subprocess.run([sys.executable, "-c", src], cwd=REPO, env=env,
                       capture_output=True, text=True, timeout=timeout)
    assert r.returncode == 0, r.stdout[-500:] + r.stderr[-500:]
    return r


def _trail(tmp_path):
    p = tmp_path / "attempts.jsonl"
    if not p.exists():
        return []
    return [json.loads(ln) for ln in p.read_text().splitlines() if ln]


def test_banking_pass_and_merge_policy(tmp_path):
    # 1. banking pass on CPU: row is not-good (mfu None) but with no
    #    snapshot on disk it must still be written, flagged
    _drive(tmp_path, "import chipup; print(chipup._bench_pass('bank'))",
           extra_env={"BENCH_CPU_TIMEOUT": "600"})
    snap_path = tmp_path / "BENCH_r05.json"
    assert snap_path.exists()
    row = json.loads(snap_path.read_text())
    assert row.get("suspect") is True        # flagged, not silent
    assert row.get("live") is True
    assert row.get("value", 0) > 0
    kinds = [e["kind"] for e in _trail(tmp_path)]
    assert "bench" in kinds

    # 2. a good row on disk must NOT be replaced by a later not-good row
    good = dict(row)
    good.update(mfu=0.42, suspect=False, value=12345.0, live=True)
    snap_path.write_text(json.dumps(good))
    _drive(tmp_path,
           "import json, chipup; "
           "bad = {'value': 1.0, 'live': True, 'suspect': True}; "
           "print(chipup._merge_bench(bad))")
    row2 = json.loads(snap_path.read_text())
    assert row2["value"] == 12345.0, "not-good row replaced a good one"
    assert any(e["kind"] == "bench_rejected" for e in _trail(tmp_path))

    # 3. replace-not-ratchet: a good live row replaces even a BETTER good
    #    row, and the replaced row's full contents land in the trail
    _drive(tmp_path,
           "import chipup; "
           "newer = {'value': 999.0, 'mfu': 0.3, 'live': True}; "
           "print(chipup._merge_bench(newer))")
    row3 = json.loads(snap_path.read_text())
    assert row3["value"] == 999.0
    replaced = [e for e in _trail(tmp_path)
                if e["kind"] == "bench_replaced_row"]
    assert replaced and replaced[-1]["row"]["value"] == 12345.0


def test_kernels_pass_installs_artifact(tmp_path):
    _drive(tmp_path, "import chipup; print(chipup._kernels_pass())",
           extra_env={"KERNELS_SMALL": "1", "KERNELS_REPEATS": "2"})
    art = tmp_path / "KERNELS_r05.json"
    assert art.exists()
    report = json.loads(art.read_text())
    assert report["all_ok"] is True
    assert set(report["kernels"]) >= {"flash_attention_fwd", "int8_matmul"}
    trail = _trail(tmp_path)
    assert any(e["kind"] == "kernels" and e["ok"] for e in trail)


def test_lm_pass_rejects_tiny_smoke(tmp_path):
    _drive(tmp_path, "import chipup; print(chipup._lm_pass())",
           extra_env={"BENCH_LM_TINY": "1"})
    assert not (tmp_path / "BENCH_LM_r05.json").exists(), \
        "a CPU tiny-smoke row must never become LM evidence"
    assert any(e["kind"] == "bench_lm_rejected" for e in _trail(tmp_path))
