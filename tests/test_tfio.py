"""TF GraphDef import/export tests — reference `utils/tf` loader/saver specs.

Foreign-graph import is exercised against GraphDefs fabricated with the wire
codec (no tensorflow in the image); round-trips check export→import numerics.
"""

import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu.keras.engine import Input, Model
from bigdl_tpu.nn.module import Sequential
from bigdl_tpu.utils import proto
from bigdl_tpu.utils import tfio
from bigdl_tpu.utils.tfio import (
    DT_FLOAT, GraphDefBuilder, UnsupportedTFOp, decode_tensor, encode_tensor,
    load_tf_graph, parse_graphdef, save_tf_graph, _attr_b, _attr_s,
    _attr_int_list, _attr_shape, _attr_type,
)


# ---------------------------------------------------------------------------
# wire codec
# ---------------------------------------------------------------------------


def test_proto_varint_roundtrip():
    m = proto.Msg().varint(1, 0).varint(1, 127).varint(1, 300).varint(1, -5)
    vals = proto.repeated_ints(proto.parse(m.bytes()), 1)
    assert vals == [0, 127, 300, -5]


def test_proto_packed_and_fixed():
    m = (proto.Msg().packed_ints(2, [1, 128, 16384])
         .f32(3, 2.5).string(4, "hello"))
    f = proto.parse(m.bytes())
    assert proto.repeated_ints(f, 2) == [1, 128, 16384]
    assert proto.get_f32(f, 3) == 2.5
    assert proto.get_str(f, 4) == "hello"


def test_proto_packed_f32():
    m = proto.Msg().packed_f32(1, [1.0, -2.0, 0.5])
    assert proto.repeated_f32(proto.parse(m.bytes()), 1) == [1.0, -2.0, 0.5]


@pytest.mark.parametrize("arr", [
    np.random.RandomState(0).randn(3, 4).astype(np.float32),
    np.arange(6, dtype=np.int32).reshape(2, 3),
    np.asarray(3.5, np.float32),
    np.asarray([True, False]),
    np.arange(4, dtype=np.int64),
])
def test_tensorproto_roundtrip(arr):
    out = decode_tensor(bytes(encode_tensor(arr).buf))
    assert out.dtype == arr.dtype
    np.testing.assert_array_equal(out, arr)


def test_tensorproto_scalar_splat():
    # TF encodes constant-filled tensors as a single value + shape
    m = (proto.Msg().varint(1, DT_FLOAT)
         .msg(2, tfio._encode_shape((2, 2)))
         .packed_f32(5, [7.0]))
    out = decode_tensor(m.bytes())
    np.testing.assert_array_equal(out, np.full((2, 2), 7.0, np.float32))


# ---------------------------------------------------------------------------
# foreign-graph import
# ---------------------------------------------------------------------------


def _mlp_graphdef(w1, b1, w2):
    g = GraphDefBuilder()
    g.node("x", "Placeholder", dtype=_attr_type(DT_FLOAT),
           shape=_attr_shape((-1, w1.shape[0])))
    g.const("dense/w", w1)
    g.const("dense/b", b1)
    g.node("dense/MatMul", "MatMul", ["x", "dense/w"],
           transpose_b=_attr_b(False))
    g.node("dense/BiasAdd", "BiasAdd", ["dense/MatMul", "dense/b"])
    g.node("relu", "Relu", ["dense/BiasAdd"])
    g.const("out/w", w2)
    g.node("out/MatMul", "MatMul", ["relu", "out/w"])
    g.node("probs", "Softmax", ["out/MatMul"])
    return g.bytes()


def test_import_mlp_matches_numpy():
    rng = np.random.RandomState(1)
    w1 = rng.randn(4, 8).astype(np.float32)
    b1 = rng.randn(8).astype(np.float32)
    w2 = rng.randn(8, 3).astype(np.float32)
    model, variables = load_tf_graph(_mlp_graphdef(w1, b1, w2))

    # MatMul+BiasAdd folded into a single Linear with bias
    layers = [n.layer for n in model.order if n.layer is not None]
    linears = [l for l in layers if isinstance(l, nn.Linear)]
    assert len(linears) == 2
    assert linears[0].with_bias and not linears[1].with_bias

    x = rng.randn(5, 4).astype(np.float32)
    y, _ = model.apply(variables, x)
    h = np.maximum(x @ w1 + b1, 0.0)
    logits = h @ w2
    expect = np.exp(logits - logits.max(-1, keepdims=True))
    expect /= expect.sum(-1, keepdims=True)
    np.testing.assert_allclose(np.asarray(y), expect, atol=1e-5)


def test_import_transpose_b_and_scalar_math():
    rng = np.random.RandomState(2)
    w = rng.randn(6, 4).astype(np.float32)  # stored transposed
    g = GraphDefBuilder()
    g.node("x", "Placeholder", dtype=_attr_type(DT_FLOAT),
           shape=_attr_shape((-1, 4)))
    g.const("w", w)
    g.node("mm", "MatMul", ["x", "w"], transpose_b=_attr_b(True))
    g.const("two", np.asarray(2.0, np.float32))
    g.node("scaled", "Mul", ["mm", "two"])
    g.const("one", np.asarray(1.0, np.float32))
    g.node("shifted", "Sub", ["scaled", "one"])
    model, variables = load_tf_graph(g.bytes())
    x = rng.randn(3, 4).astype(np.float32)
    y, _ = model.apply(variables, x)
    np.testing.assert_allclose(np.asarray(y), (x @ w.T) * 2.0 - 1.0,
                               rtol=1e-5, atol=1e-5)


def test_import_identity_chain_and_residual_add():
    rng = np.random.RandomState(3)
    w = rng.randn(4, 4).astype(np.float32)
    g = GraphDefBuilder()
    g.node("x", "Placeholder", dtype=_attr_type(DT_FLOAT),
           shape=_attr_shape((-1, 4)))
    g.const("w/raw", w)
    g.node("w", "Identity", ["w/raw"])  # frozen graphs wrap vars in Identity
    g.node("mm", "MatMul", ["x", "w"])
    g.node("res", "AddV2", ["mm", "x"])  # residual: both inputs are tensors
    model, variables = load_tf_graph(g.bytes())
    x = rng.randn(2, 4).astype(np.float32)
    y, _ = model.apply(variables, x)
    np.testing.assert_allclose(np.asarray(y), x @ w + x, rtol=1e-5, atol=1e-5)


def test_import_unsupported_op_raises():
    g = GraphDefBuilder()
    g.node("x", "Placeholder", dtype=_attr_type(DT_FLOAT),
           shape=_attr_shape((-1, 4)))
    g.node("weird", "SomeCustomOp", ["x"])
    with pytest.raises(UnsupportedTFOp, match="SomeCustomOp"):
        load_tf_graph(g.bytes())


def test_import_conv_pool_mean_graph():
    rng = np.random.RandomState(4)
    w = rng.randn(3, 3, 3, 8).astype(np.float32)
    b = rng.randn(8).astype(np.float32)
    g = GraphDefBuilder()
    g.node("img", "Placeholder", dtype=_attr_type(DT_FLOAT),
           shape=_attr_shape((-1, 16, 16, 3)))
    g.const("k", w)
    g.const("kb", b)
    g.node("conv", "Conv2D", ["img", "k"],
           strides=_attr_int_list([1, 1, 1, 1]), padding=_attr_s(b"SAME"))
    g.node("conv/bias", "BiasAdd", ["conv", "kb"])
    g.node("act", "Relu6", ["conv/bias"])
    g.node("pool", "MaxPool", ["act"], ksize=_attr_int_list([1, 2, 2, 1]),
           strides=_attr_int_list([1, 2, 2, 1]), padding=_attr_s(b"VALID"))
    g.const("axes", np.asarray([1, 2], np.int32))
    g.node("gap", "Mean", ["pool", "axes"])
    model, variables = load_tf_graph(g.bytes())
    x = rng.randn(2, 16, 16, 3).astype(np.float32)
    y, _ = model.apply(variables, x)
    assert np.asarray(y).shape == (2, 8)
    # conv bias got folded
    convs = [n.layer for n in model.order
             if n.layer is not None and isinstance(n.layer, nn.Conv2D)]
    assert len(convs) == 1 and convs[0].with_bias


# ---------------------------------------------------------------------------
# export → import round-trips
# ---------------------------------------------------------------------------


def test_roundtrip_sequential_cnn(tmp_path):
    import jax

    model = Sequential([
        nn.Conv2D(3, 8, 3, padding="SAME"),
        nn.BatchNorm(8),
        nn.ReLU(),
        nn.MaxPool2D(2),
        nn.Flatten(),
        nn.Linear(8 * 8 * 8, 10),
        nn.SoftMax(),
    ])
    rng = np.random.RandomState(5)
    x = rng.randn(4, 16, 16, 3).astype(np.float32)
    variables = model.init(jax.random.PRNGKey(0), x)
    # non-trivial BN stats so the export path is actually checked
    k = [k for k in variables["state"] if "BatchNorm" in k][0]
    variables["state"][k]["running_mean"] = rng.randn(8).astype(np.float32) * .1
    variables["state"][k]["running_var"] = (
        1.0 + 0.1 * rng.rand(8)).astype(np.float32)

    path = str(tmp_path / "model.pb")
    save_tf_graph(model, variables, sample=x, path=path)
    model2, vars2 = load_tf_graph(path)

    y1, _ = model.apply(variables, x)
    y2, _ = model2.apply(vars2, x)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2),
                               rtol=1e-4, atol=1e-5)


def test_roundtrip_functional_two_branch():
    import jax

    inp = Input((12, 12, 2))
    a = nn.Conv2D(2, 4, 3, padding="SAME")(inp)
    a = nn.ReLU()(a)
    b = nn.Conv2D(2, 4, 1, padding="SAME")(inp)
    merged = nn.CAddTable()([a, b])
    out = nn.JoinTable(3)([merged, b])
    model = Model(inp, out)

    rng = np.random.RandomState(6)
    x = rng.randn(2, 12, 12, 2).astype(np.float32)
    variables = model.init(jax.random.PRNGKey(1), x)

    data = save_tf_graph(model, variables, sample=x)
    model2, vars2 = load_tf_graph(data)

    y1, _ = model.apply(variables, x)
    y2, _ = model2.apply(vars2, x)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2),
                               rtol=1e-4, atol=1e-5)


def test_roundtrip_activations_and_pad():
    import jax

    model = Sequential([
        nn.ZeroPadding2D(1),
        nn.AvgPool2D(2, padding=0),
        nn.Flatten(),
        nn.Linear(2 * 9 * 9, 6),
        nn.Tanh(),
        nn.Dropout(0.5),
        nn.LeakyReLU(0.1),
        nn.LogSoftMax(),
    ])
    rng = np.random.RandomState(7)
    x = rng.randn(3, 16, 16, 2).astype(np.float32)
    variables = model.init(jax.random.PRNGKey(2), x)
    data = save_tf_graph(model, variables, sample=x)
    model2, vars2 = load_tf_graph(data)
    y1, _ = model.apply(variables, x)
    y2, _ = model2.apply(vars2, x)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2),
                               rtol=1e-4, atol=1e-5)


def test_parse_graphdef_structure():
    g = GraphDefBuilder()
    g.node("x", "Placeholder", dtype=_attr_type(DT_FLOAT))
    g.const("c", np.ones((2,), np.float32))
    g.node("y", "Relu", ["x"])
    nodes = parse_graphdef(g.bytes())
    assert [n.op for n in nodes] == ["Placeholder", "Const", "Relu"]
    assert nodes[2].inputs == ["x"]
    np.testing.assert_array_equal(nodes[1].attrs["value"].tensor,
                                  np.ones((2,), np.float32))


def test_import_deep_chain_no_recursion_limit():
    """Frozen graphs routinely chain 1000+ nodes; toposort must not recurse."""
    g = GraphDefBuilder()
    g.node("x", "Placeholder", dtype=_attr_type(DT_FLOAT),
           shape=_attr_shape((-1, 4)))
    prev = "x"
    for i in range(1500):
        prev = g.node(f"id_{i}", "Identity", [prev])
    g.node("out", "Relu", [prev])
    model, variables = load_tf_graph(g.bytes())
    x = np.random.RandomState(0).randn(2, 4).astype(np.float32)
    y, _ = model.apply(variables, x)
    np.testing.assert_allclose(np.asarray(y), np.maximum(x, 0))
