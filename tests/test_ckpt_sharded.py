"""Per-process (ZeRO-sharded) checkpoint writes — the Orbax-style
pod-scale posture: each host writes 1/n of the optimizer state with no
cross-host allgather, the manifest is written last by process 0, and
readers trust a sharded checkpoint only when every shard file exists.
Reassembly places flat slices at recorded offsets, so loading works for
ANY process count (free resharding)."""

import json
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import jax

from bigdl_tpu.optim.checkpoint import (latest_checkpoint, load_checkpoint,
                                        local_opt_shards, save_checkpoint)

# ---------------------------------------------------------------------------
# unit tier: format + reassembly, shards simulated in-process


def _template():
    return {"momentum": np.zeros((12,), np.float32),
            "count": np.zeros((), np.int32)}


def test_sharded_roundtrip_two_simulated_writers(tmp_path):
    root = str(tmp_path / "ck")
    full = np.arange(12, dtype=np.float32)
    shard0 = {"momentum": full[:6], "momentum@offset": np.asarray(0),
              "count": np.asarray(7, np.int32)}
    shard1 = {"momentum": full[6:], "momentum@offset": np.asarray(6),
              "count": np.asarray(7, np.int32)}
    # writer order mirrors the real multi-writer race: shard 1 first
    save_checkpoint(root, 4, opt_shards=shard1, shard_index=1,
                    shard_count=2)
    d = save_checkpoint(root, 4, opt_shards=shard0, shard_index=0,
                        shard_count=2, flat_params=np.ones(3),
                        model_state={}, driver_state={"epoch": 2})
    assert latest_checkpoint(root) == d
    flat, opt, _ms, driver, _ema = load_checkpoint(
        d, opt_state_template=_template(), model_state_template={})
    np.testing.assert_array_equal(opt["momentum"], full)
    assert int(opt["count"]) == 7
    assert driver == {"epoch": 2}


def test_incomplete_shard_set_is_invisible(tmp_path):
    """Manifest present but a shard missing (async writer lag or crash):
    the checkpoint must not be offered for resume."""
    root = str(tmp_path / "ck")
    full = np.arange(12, dtype=np.float32)
    # only shard 0 of 2 lands, then the manifest (process 0 path)
    save_checkpoint(root, 6, opt_shards={
        "momentum": full[:6], "momentum@offset": np.asarray(0),
        "count": np.asarray(1, np.int32)}, shard_index=0, shard_count=2,
        flat_params=np.ones(3), model_state={}, driver_state={})
    assert latest_checkpoint(root) is None
    # the laggard shard arrives -> checkpoint becomes visible
    save_checkpoint(root, 6, opt_shards={
        "momentum": full[6:], "momentum@offset": np.asarray(6),
        "count": np.asarray(1, np.int32)}, shard_index=1, shard_count=2)
    assert latest_checkpoint(root).endswith("ckpt-6")


def test_stale_attempt_shard_never_certified(tmp_path):
    """Crashed attempt A leaves shard 1; attempt B (new token) writes
    shard 0 + manifest then dies before shard 1.  The manifest's token
    must NOT be satisfied by attempt A's stale shard — the checkpoint
    stays invisible until attempt B's own shard 1 exists, and loading
    then reads only token-B data."""
    root = str(tmp_path / "ck")
    full = np.arange(12, dtype=np.float32)
    stale = {"momentum": -np.ones(6, np.float32),
             "momentum@offset": np.asarray(6),
             "count": np.asarray(99, np.int32)}
    save_checkpoint(root, 4, opt_shards=stale, shard_index=1,
                    shard_count=2, attempt="aaaaaaaa")  # attempt A, crashed
    save_checkpoint(root, 4, opt_shards={
        "momentum": full[:6], "momentum@offset": np.asarray(0),
        "count": np.asarray(1, np.int32)}, shard_index=0, shard_count=2,
        attempt="bbbbbbbb", flat_params=np.ones(3), model_state={},
        driver_state={})
    assert latest_checkpoint(root) is None  # A's shard 1 must not count
    save_checkpoint(root, 4, opt_shards={
        "momentum": full[6:], "momentum@offset": np.asarray(6),
        "count": np.asarray(1, np.int32)}, shard_index=1, shard_count=2,
        attempt="bbbbbbbb")
    latest = latest_checkpoint(root)
    assert latest is not None
    _f, opt, *_ = load_checkpoint(
        latest, opt_state_template=_template(), model_state_template={})
    np.testing.assert_array_equal(opt["momentum"], full)  # not -1s
    assert int(opt["count"]) == 1


def test_reassembly_across_different_shard_counts(tmp_path):
    """A 3-writer checkpoint loads fine regardless of the current
    topology — resharding is free."""
    root = str(tmp_path / "ck")
    full = np.arange(12, dtype=np.float32)
    bounds = [(0, 4), (4, 8), (8, 12)]
    for i, (lo, hi) in enumerate(bounds):
        kw = {}
        if i == 0:
            kw = dict(flat_params=np.zeros(2), model_state={},
                      driver_state={})
        save_checkpoint(root, 9, opt_shards={
            "momentum": full[lo:hi], "momentum@offset": np.asarray(lo),
            "count": np.asarray(0, np.int32)},
            shard_index=i, shard_count=3, **kw)
    _f, opt, *_ = load_checkpoint(
        latest_checkpoint(root), opt_state_template=_template(),
        model_state_template={})
    np.testing.assert_array_equal(opt["momentum"], full)


def test_local_opt_shards_single_process_mesh():
    """On a single process every device shard is addressable: the local
    contribution is the WHOLE leaf at offset 0, replicated leaves pass
    through, and the flat keys match the checkpoint's path convention."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = jax.devices()
    mesh = Mesh(np.array(devs), ("data",))
    vec = np.arange(len(devs) * 4, dtype=np.float32)
    tree = {
        "momentum": jax.device_put(
            vec, NamedSharding(mesh, P("data"))),
        "count": jax.device_put(
            np.asarray(3, np.int32), NamedSharding(mesh, P())),
    }
    flat = local_opt_shards(tree)
    np.testing.assert_array_equal(flat["momentum"], vec)
    assert int(flat["momentum@offset"]) == 0
    assert int(flat["count"]) == 3
    assert "count@offset" not in flat


def test_local_opt_shards_rejects_non_leading_axis_sharding():
    """Same-start dedup treats equal leading offsets as replicas, which is
    only sound for leading-axis (ZeRO) sharding — a trailing-axis layout
    must fail loudly at SAVE time, not with a shape mismatch at load."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip("needs >= 2 devices")
    mesh = Mesh(np.array(devs), ("data",))
    arr = jax.device_put(
        np.arange(2 * len(devs), dtype=np.float32).reshape(2, len(devs)),
        NamedSharding(mesh, P(None, "data")))
    with pytest.raises(ValueError, match="non-leading axis"):
        local_opt_shards({"m": arr})


# ---------------------------------------------------------------------------
# GC: the keep set must count only checkpoints a reader would accept


def _write_complete(root, step, **extra):
    full = np.arange(12, dtype=np.float32)
    save_checkpoint(root, step, opt_shards={
        "momentum": full[6:], "momentum@offset": np.asarray(6),
        "count": np.asarray(1, np.int32)}, shard_index=1, shard_count=2)
    save_checkpoint(root, step, opt_shards={
        "momentum": full[:6], "momentum@offset": np.asarray(0),
        "count": np.asarray(1, np.int32)}, shard_index=0, shard_count=2,
        flat_params=np.ones(3), model_state={}, driver_state={}, **extra)


def _write_manifest_only(root, step, **extra):
    """Manifest present, shard 1 of 2 missing — what a persistently
    failing async shard writer leaves behind."""
    full = np.arange(12, dtype=np.float32)
    save_checkpoint(root, step, opt_shards={
        "momentum": full[:6], "momentum@offset": np.asarray(0),
        "count": np.asarray(1, np.int32)}, shard_index=0, shard_count=2,
        flat_params=np.ones(3), model_state={}, driver_state={}, **extra)


def test_gc_never_deletes_newest_shard_complete(tmp_path):
    """ADVICE r5 medium: manifest-present-but-shard-incomplete dirs must
    not count toward keep_last — with keep_last such dirs piling up, the
    old GC deleted the only restorable checkpoint."""
    root = str(tmp_path / "ck")
    _write_complete(root, 2)
    for step in (4, 6, 8):  # three incomplete dirs, keep_last=3
        _write_manifest_only(root, step, keep_last=3)
    # ckpt-2 is the ONLY restorable checkpoint: it must survive
    assert latest_checkpoint(root).endswith("ckpt-2")
    assert os.path.isdir(str(tmp_path / "ck" / "ckpt-2"))

    # once a NEWER complete checkpoint exists, older garbage becomes
    # collectable: complete-but-out-of-window dirs immediately, shard-
    # incomplete dirs only after a GRACE scan (a single exists() blip on
    # an object store must not delete a restorable checkpoint)
    _write_complete(root, 10, keep_last=1)
    assert latest_checkpoint(root).endswith("ckpt-10")
    left = sorted(n for n in os.listdir(root) if n.startswith("ckpt-"))
    assert left == ["ckpt-10", "ckpt-4", "ckpt-6", "ckpt-8"], left
    # the second scan agrees they are incomplete -> deleted
    _write_complete(root, 12, keep_last=1)
    left = sorted(n for n in os.listdir(root) if n.startswith("ckpt-"))
    assert left == ["ckpt-12"], left


def test_gc_keeps_incomplete_dirs_newer_than_newest_valid(tmp_path):
    """A shard-incomplete dir NEWER than the newest complete one may be a
    write in flight (async shard writers are unbarriered): not garbage."""
    root = str(tmp_path / "ck")
    _write_complete(root, 2)
    _write_manifest_only(root, 4, keep_last=1)
    names = set(os.listdir(root))
    assert {"ckpt-2", "ckpt-4"} <= names
    # the laggard shard lands: ckpt-4 becomes the newest restorable
    full = np.arange(12, dtype=np.float32)
    save_checkpoint(root, 4, opt_shards={
        "momentum": full[6:], "momentum@offset": np.asarray(6),
        "count": np.asarray(1, np.int32)}, shard_index=1, shard_count=2)
    assert latest_checkpoint(root).endswith("ckpt-4")


def test_gc_spares_checkpoint_with_unreadable_manifest(tmp_path,
                                                       monkeypatch):
    """A transient manifest READ failure makes a checkpoint's completeness
    unknown — readers skip it for now, but GC must not delete it: the blip
    may be hiding the only restorable state."""
    from bigdl_tpu.optim import checkpoint as ckpt_mod
    from bigdl_tpu.utils import storage as storage_mod

    root = str(tmp_path / "ck")
    _write_complete(root, 2)

    real_read = storage_mod.read_json

    def flaky_read(path):
        if "ckpt-2" in path:
            raise OSError("transient storage blip")
        return real_read(path)

    monkeypatch.setattr(ckpt_mod.storage, "read_json", flaky_read)
    # unreadable -> not offered to readers this scan...
    assert latest_checkpoint(root) is None
    # ...and a newer complete checkpoint + tight keep_last still must
    # not GC the unreadable (possibly restorable) ckpt-2
    _write_complete(root, 4, keep_last=1)
    assert os.path.isdir(os.path.join(root, "ckpt-2"))
    # blip clears: ckpt-2 is fully visible again
    monkeypatch.setattr(ckpt_mod.storage, "read_json", real_read)
    assert {n for n in os.listdir(root) if n.startswith("ckpt-")} == \
        {"ckpt-2", "ckpt-4"}
    assert latest_checkpoint(root).endswith("ckpt-4")


def test_gc_deletes_nothing_without_any_complete_checkpoint(tmp_path):
    root = str(tmp_path / "ck")
    for step in (2, 4, 6, 8):
        _write_manifest_only(root, step, keep_last=2)
    assert latest_checkpoint(root) is None
    names = sorted(n for n in os.listdir(root) if n.startswith("ckpt-"))
    assert names == ["ckpt-2", "ckpt-4", "ckpt-6", "ckpt-8"], names


# ---------------------------------------------------------------------------
# integration tier: TRUE 2-process training with sharded="auto" + resume

pytestmark_integration = pytest.mark.slow

_WORKER = textwrap.dedent("""
    import os, sys
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")

    from bigdl_tpu.data.dataset import ArrayDataSet
    from bigdl_tpu import nn
    from bigdl_tpu.nn.criterion import MSECriterion
    from bigdl_tpu.optim import Optimizer, SGD, Trigger
    from bigdl_tpu.runtime.engine import init_engine

    init_engine()
    assert jax.process_count() == 2, jax.process_count()
    ckpt_dir = os.environ["CKPT_DIR"]
    n_iters = int(os.environ["N_ITERS"])
    rs = np.random.RandomState(0)
    w_true = np.asarray([[2.0], [-1.0]], np.float32)
    x = rs.rand(128, 2).astype(np.float32)
    y = x @ w_true
    model = nn.Linear(2, 1)
    opt = (Optimizer(model, ArrayDataSet(x, y), MSECriterion(),
                     batch_size=32, seed=11)
           .set_optim_method(SGD(learning_rate=0.3, momentum=0.9))
           .set_end_when(Trigger.max_iteration(n_iters)))
    opt.set_checkpoint(ckpt_dir, Trigger.several_iteration(2))
    opt.log_every = 100
    trained = opt.optimize()
    w = np.asarray(trained.variables["params"]["weight"])
    print(f"RANK{jax.process_index()}_W={float(w[0,0]):.6f},"
          f"{float(w[1,0]):.6f}")
""")


@pytest.mark.slow
def test_two_process_sharded_checkpoint_resume(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    ckpt_dir = tmp_path / "ckpts"
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pythonpath = os.pathsep.join(
        p for p in [repo_root, os.environ.get("PYTHONPATH")] if p)

    def run_gang(n_iters):
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        procs = []
        for r in range(2):
            env = dict(os.environ,
                       BIGDL_TPU_COORDINATOR=f"127.0.0.1:{port}",
                       BIGDL_TPU_NUM_PROCESSES="2",
                       BIGDL_TPU_PROCESS_ID=str(r),
                       JAX_PLATFORMS="cpu",
                       CKPT_DIR=str(ckpt_dir), N_ITERS=str(n_iters),
                       PYTHONPATH=pythonpath)
            env.pop("XLA_FLAGS", None)  # one device per process
            procs.append(subprocess.Popen(
                [sys.executable, str(script)], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True))
        outs = []
        for p in procs:
            try:
                outs.append(p.communicate(timeout=420)[0])
            except subprocess.TimeoutExpired:
                p.kill()
                outs.append(p.communicate()[0])
        assert [p.returncode for p in procs] == [0, 0], \
            f"--- rank0:\n{outs[0]}\n--- rank1:\n{outs[1]}"
        return outs

    run_gang(4)
    latest = latest_checkpoint(str(ckpt_dir))
    assert latest is not None and latest.endswith("ckpt-4")
    manifest = json.load(open(os.path.join(latest, "manifest.json")))
    assert manifest["opt_shards"] == 2
    tok = manifest["opt_shards_attempt"]
    assert len(tok) == 8  # broadcast uuid: all writers agreed on it
    shard_files = sorted(f for f in os.listdir(latest)
                         if f.startswith("opt_state.shard"))
    assert shard_files == [
        f"opt_state.shard00000-of-00002.{tok}.npz",
        f"opt_state.shard00001-of-00002.{tok}.npz"], shard_files
    assert not os.path.exists(os.path.join(latest, "opt_state.npz"))

    # second gang resumes from ckpt-4 and continues to 8; ranks agree
    outs = run_gang(8)
    assert latest_checkpoint(str(ckpt_dir)).endswith("ckpt-8")
    ws = sorted(ln for o in outs for ln in o.splitlines() if "_W=" in ln)
    assert len(ws) == 2
    assert ws[0].split("=")[1] == ws[1].split("=")[1], ws
