"""AutoML (hp DSL, searchers, AutoEstimator) and Chronos-equivalent AutoTS.

Mirrors the reference test style (SURVEY.md §5): tiny synthetic data,
local execution, assert the search ran and the best model beats chance.
"""

import numpy as np
import pandas as pd
import pytest

from bigdl_tpu.automl import AutoEstimator, GridSearcher, RandomSearcher, hp
from bigdl_tpu.automl.hp import grid_points, sample_space


class TestHp:
    def test_samplers(self):
        rng = np.random.default_rng(0)
        space = {
            "lr": hp.loguniform(1e-4, 1e-1),
            "units": hp.choice([16, 32]),
            "depth": hp.randint(1, 4),
            "frac": hp.uniform(0.0, 1.0),
            "q": hp.quniform(0.0, 1.0, 0.25),
            "fixed": 7,
            "nested": {"k": hp.choice(["a", "b"])},
        }
        cfg = sample_space(space, rng)
        assert 1e-4 <= cfg["lr"] <= 1e-1
        assert cfg["units"] in (16, 32)
        assert 1 <= cfg["depth"] < 4
        assert cfg["q"] in (0.0, 0.25, 0.5, 0.75, 1.0)
        assert cfg["fixed"] == 7
        assert cfg["nested"]["k"] in ("a", "b")

    def test_grid_points(self):
        pts = grid_points({"a": hp.choice([1, 2]), "b": hp.choice([3, 4]),
                           "c": "x"})
        assert len(pts) == 4
        assert all(p["c"] == "x" for p in pts)
        with pytest.raises(ValueError):
            grid_points({"a": hp.uniform(0, 1)})


class TestSearchers:
    def test_random_min(self):
        s = RandomSearcher(mode="min", seed=0)
        best = s.run(lambda c: (c["x"] - 3) ** 2,
                     {"x": hp.uniform(0, 10)}, n_sampling=25)
        assert abs(best.config["x"] - 3) < 2.0
        assert len(s.results) == 25

    def test_grid_max(self):
        s = GridSearcher(mode="max")
        best = s.run(lambda c: c["x"] * c["y"],
                     {"x": hp.choice([1, 2, 3]), "y": hp.choice([5, 7])},
                     n_sampling=0)
        assert best.config == {"x": 3, "y": 7}

    def test_failed_trials_skipped(self):
        def trial(c):
            if c["x"] == 1:
                raise RuntimeError("boom")
            return c["x"]

        s = GridSearcher(mode="min")
        best = s.run(trial, {"x": hp.choice([1, 2, 3])}, n_sampling=0)
        assert best.config["x"] == 2
        assert s.results[0].error is not None


class TestAutoEstimator:
    def test_fit_linear_regression(self):
        from bigdl_tpu.nn.criterion import MSECriterion
        from bigdl_tpu.nn.layers import Linear
        from bigdl_tpu.nn.module import Sequential
        from bigdl_tpu.optim.optim_method import Adam

        rng = np.random.RandomState(0)
        x = rng.randn(128, 8).astype(np.float32)
        w = rng.randn(8, 1).astype(np.float32)
        y = x @ w

        auto = AutoEstimator(
            model_creator=lambda cfg: Sequential(
                [Linear(8, cfg["units"]), Linear(cfg["units"], 1)]),
            optimizer_creator=lambda cfg: Adam(learning_rate=cfg["lr"]),
            loss_creator=lambda cfg: MSECriterion(),
            metric="loss", mode="min")
        auto.fit((x, y), search_space={
            "units": hp.choice([4, 8]),
            "lr": hp.choice([1e-2, 3e-2]),
        }, n_sampling=3, epochs=12, batch_size=32)
        assert auto.best_result.metric < 0.5
        assert auto.get_best_config()["units"] in (4, 8)
        assert auto.get_best_model() is not None


def _series(n=300):
    t = np.arange(n)
    return pd.DataFrame({
        "dt": pd.date_range("2025-01-01", periods=n, freq="h"),
        "value": (np.sin(2 * np.pi * t / 24)
                  + 0.05 * np.random.RandomState(0).randn(n)),
    })


class TestAutoTS:
    def test_autots_pipeline(self, tmp_path):
        from bigdl_tpu.forecast.autots import AutoTSEstimator, TSPipeline
        from bigdl_tpu.forecast.tsdataset import TSDataset

        tsdata = TSDataset.from_pandas(_series(), dt_col="dt",
                                       target_col="value").scale()
        auto = AutoTSEstimator(
            model="lstm",
            search_space={"hidden_dim": hp.choice([16, 32]),
                          "lr": hp.choice([1e-2])},
            past_seq_len=hp.choice([12, 24]), future_seq_len=4, seed=0)
        pipeline = auto.fit(tsdata, epochs=2, n_sampling=2)
        assert auto.get_best_config()["past_seq_len"] in (12, 24)

        pred = pipeline.predict(tsdata)
        assert pred.shape[1:] == (4, 1)
        ev = pipeline.evaluate(tsdata, metrics=["mse", "mae"])
        assert set(ev) == {"mse", "mae"}
        assert np.isfinite(ev["mse"])

        # save/load round trip
        p = str(tmp_path / "tsppl")
        pipeline.save(p)
        loaded = TSPipeline.load(p)
        pred2 = loaded.predict(tsdata)
        np.testing.assert_allclose(pred2, pred, rtol=1e-4, atol=1e-5)
        # a LOADED pipeline can be re-saved (its forecaster re-records
        # constructor args) and a manually built pipeline can be saved too
        loaded.save(str(tmp_path / "tsppl2"))
        re = TSPipeline.load(str(tmp_path / "tsppl2"))
        np.testing.assert_allclose(re.predict(tsdata), pred, rtol=1e-4,
                                   atol=1e-5)

    def test_predict_includes_final_window_and_unscales(self):
        from bigdl_tpu.forecast.autots import TSPipeline
        from bigdl_tpu.forecast.forecaster import LSTMForecaster
        from bigdl_tpu.forecast.tsdataset import TSDataset

        df = _series(260)
        # train on a scaled dataset, keep values in original units offset
        df["value"] = df["value"] * 50 + 500
        tsdata = TSDataset.from_pandas(df, dt_col="dt",
                                       target_col="value").scale()
        fc = LSTMForecaster(past_seq_len=24, future_seq_len=4,
                            input_feature_num=1, output_feature_num=1,
                            hidden_dim=16)
        x, y = tsdata.roll(24, 4).to_numpy()
        fc.fit((x, y), epochs=3)
        ppl = TSPipeline(fc, 24, 4, scaler=tsdata.scaler)

        fresh = TSDataset.from_pandas(df, dt_col="dt", target_col="value")
        before = fresh.df["value"].to_numpy().copy()
        pred = ppl.predict(fresh)
        # horizon=0 roll => one window per trailing position incl. the LAST
        assert pred.shape == (260 - 24 + 1, 4, 1)
        # outputs are inverse-transformed to original units (~500-ish scale)
        assert 300 < float(np.mean(pred)) < 700, float(np.mean(pred))
        # the caller's dataset is NOT mutated by internal scaling
        np.testing.assert_array_equal(fresh.df["value"].to_numpy(), before)
        assert fresh.scaler is None
        # evaluate reports metrics in the same original units as predict
        ev = ppl.evaluate(fresh, metrics=["mse", "mae"])
        assert ev["mae"] < 200, ev  # original-unit scale, not z-scores

    def test_manual_pipeline_save(self, tmp_path):
        from bigdl_tpu.forecast.autots import TSPipeline
        from bigdl_tpu.forecast.forecaster import LSTMForecaster
        from bigdl_tpu.forecast.tsdataset import TSDataset

        tsdata = TSDataset.from_pandas(_series(200), dt_col="dt",
                                       target_col="value")
        fc = LSTMForecaster(past_seq_len=12, future_seq_len=2,
                            input_feature_num=1, output_feature_num=1,
                            hidden_dim=8)
        x, y = tsdata.roll(12, 2).to_numpy()
        fc.fit((x, y), epochs=1)
        ppl = TSPipeline(fc, 12, 2)
        p = str(tmp_path / "manual")
        ppl.save(p)
        loaded = TSPipeline.load(p)
        assert loaded.forecaster.hidden_dim == 8
        np.testing.assert_allclose(loaded.predict(x), ppl.predict(x),
                                   rtol=1e-4, atol=1e-5)

    def test_searcher_drops_loser_artifacts(self):
        s = RandomSearcher(mode="min", seed=0)
        s.run(lambda c: (c["x"], object()), {"x": hp.choice([3, 1, 2])},
              n_sampling=6)
        keep = [r for r in s.results if r.artifacts is not None]
        assert len(keep) == 1 and keep[0].metric == 1


class TestAdvancedSearchers:
    def test_successive_halving_promotes_best_and_scales_budget(self):
        from bigdl_tpu.automl import SuccessiveHalvingSearcher, hp

        calls = []

        def trial(cfg):
            calls.append((cfg["x"], cfg["epochs"]))
            # quadratic loss improving with budget; best x is 0.1
            return (cfg["x"] - 0.1) ** 2 + 1.0 / cfg["epochs"]

        s = SuccessiveHalvingSearcher(mode="min", seed=0, eta=3,
                                      min_budget=1, max_budget=9)
        best = s.run(trial, {"x": hp.uniform(0, 1)}, n_sampling=9)
        budgets = sorted({b for _, b in calls})
        assert budgets == [1, 3, 9]           # three rungs
        n_at = {b: sum(1 for _, bb in calls if bb == b) for b in budgets}
        assert n_at[1] == 9 and n_at[3] == 3 and n_at[9] == 1
        assert best.config["epochs"] == 9
        assert abs(best.config["x"] - 0.1) < 0.35

    def test_successive_halving_survives_failing_trials(self):
        from bigdl_tpu.automl import SuccessiveHalvingSearcher, hp

        def trial(cfg):
            if cfg["x"] > 0.8:
                raise RuntimeError("boom")
            return cfg["x"]

        s = SuccessiveHalvingSearcher(mode="min", seed=1, min_budget=1,
                                      max_budget=3, eta=3)
        best = s.run(trial, {"x": hp.uniform(0, 1)}, n_sampling=6)
        assert best.error is None and best.metric <= 0.8

    def test_tpe_beats_pure_random_on_narrow_optimum(self):
        from bigdl_tpu.automl import RandomSearcher, TPESearcher, hp

        def trial(cfg):
            return (cfg["lr"] - 0.01) ** 2 * 1e4 + (cfg["h"] - 32) ** 2 / 100

        space = {"lr": hp.loguniform(1e-4, 1.0), "h": hp.randint(8, 128)}
        tpe = TPESearcher(mode="min", seed=3, n_warmup=5)
        best_tpe = tpe.run(trial, space, n_sampling=30)
        assert best_tpe.error is None
        # TPE concentrates: its best should be decent in absolute terms
        assert best_tpe.metric < 5.0

    def test_tpe_proposals_concentrate_near_good_history(self):
        """Deterministic check of the proposal machinery: with a history
        whose good quantile clusters at lr=0.01, proposals must land nearer
        0.01 than fresh loguniform samples do."""
        from bigdl_tpu.automl import TPESearcher, hp
        from bigdl_tpu.automl.search import TrialResult

        space = {"lr": hp.loguniform(1e-4, 1.0)}
        s = TPESearcher(mode="min", seed=0)
        rng = np.random.default_rng(1)
        # good cluster at ~0.01 (low metric), bad spread elsewhere
        for _ in range(8):
            lr = float(10 ** rng.uniform(-2.2, -1.8))
            s.results.append(TrialResult({"lr": lr}, 0.01))
        for _ in range(24):
            lr = float(10 ** rng.uniform(-4, 0))
            s.results.append(TrialResult({"lr": lr}, 10.0))
        props = [s._propose(space)["lr"] for _ in range(20)]
        d_prop = np.median(np.abs(np.log10(props) + 2))
        rand = [space["lr"].sample(rng) for _ in range(200)]
        d_rand = np.median(np.abs(np.log10(rand) + 2))
        assert d_prop < d_rand

    def test_tpe_handles_choice_axes(self):
        from bigdl_tpu.automl import TPESearcher, hp

        def trial(cfg):
            return 0.0 if cfg["act"] == "relu" else 1.0

        s = TPESearcher(mode="min", seed=0, n_warmup=4)
        best = s.run(trial, {"act": hp.choice(["relu", "tanh", "gelu"])},
                     n_sampling=20)
        assert best.config["act"] == "relu"
        picked = [r.config["act"] for r in s.results[8:]]
        assert picked.count("relu") > len(picked) / 3

    def test_tpe_nested_space(self):
        from bigdl_tpu.automl import TPESearcher, hp

        def trial(cfg):
            assert not hasattr(cfg["model"]["lr"], "sample")  # resolved
            return (cfg["model"]["lr"] - 0.1) ** 2

        s = TPESearcher(mode="min", seed=0, n_warmup=3)
        best = s.run(trial, {"model": {"lr": hp.uniform(0, 1)}},
                     n_sampling=12)
        assert best.error is None
        assert all(r.error is None for r in s.results)

    def test_successive_halving_lone_survivor_reaches_max_budget(self):
        from bigdl_tpu.automl import SuccessiveHalvingSearcher, hp

        budgets = []

        def trial(cfg):
            budgets.append(cfg["epochs"])
            return cfg["x"]

        s = SuccessiveHalvingSearcher(mode="min", seed=0, eta=3,
                                      min_budget=1, max_budget=9)
        best = s.run(trial, {"x": hp.uniform(0, 1)}, n_sampling=2)
        assert best.config["epochs"] == 9  # lone survivor still promoted
        assert 9 in budgets


class TestSearcherRobustness:
    def test_nan_metric_never_wins(self):
        # a diverged trial (NaN loss) must be treated as failed, not sorted
        # to the top (NaN comparisons are all-False under sorted())
        from bigdl_tpu.automl import (SuccessiveHalvingSearcher, TPESearcher,
                                      hp)

        def trial(cfg):
            return float("nan") if cfg["lr"] > 0.5 else cfg["lr"]

        for seed in range(4):
            s = SuccessiveHalvingSearcher(mode="min", seed=seed,
                                          min_budget=1, max_budget=3)
            best = s.run(trial, {"lr": hp.uniform(0, 1)}, n_sampling=6)
            assert np.isfinite(best.metric)

        s = TPESearcher(mode="min", seed=1, n_warmup=3)
        best = s.run(trial, {"lr": hp.uniform(0, 1)}, n_sampling=10)
        assert np.isfinite(best.metric)
        # NaN trials are recorded as errors, excluded from the Parzen split
        assert all(r.error is not None or np.isfinite(r.metric)
                   for r in s.results)

    def test_tpe_quniform_stays_on_grid(self):
        from bigdl_tpu.automl import TPESearcher, hp

        def trial(cfg):
            assert cfg["bs"] % 16 == 0, cfg["bs"]  # the q contract
            return abs(cfg["bs"] - 64)

        s = TPESearcher(mode="min", seed=0, n_warmup=3)
        best = s.run(trial, {"bs": hp.quniform(16, 128, 16)}, n_sampling=12)
        assert best.error is None and best.config["bs"] % 16 == 0


def test_parallel_trials_concurrent_wall_clock():
    """VERDICT r2 item 5: independent trials run CONCURRENTLY. The trial
    body blocks 0.3s (stands in for host-side work + an XLA execution,
    during both of which the GIL is released); 8 trials at parallel=8 must
    finish in ~1 wave, >= 4x faster than sequentially."""
    import time

    from bigdl_tpu.automl import RandomSearcher, hp

    space = {"lr": hp.uniform(0.01, 0.1)}

    def trial(config):
        time.sleep(0.3)
        return config["lr"]

    seq = RandomSearcher(mode="min", seed=0)
    t0 = time.perf_counter()
    seq.run(trial, space, n_sampling=8)
    t_seq = time.perf_counter() - t0

    par = RandomSearcher(mode="min", seed=0)
    t0 = time.perf_counter()
    best = par.run(trial, space, n_sampling=8, parallel=8)
    t_par = time.perf_counter() - t0

    assert t_seq / t_par >= 4.0, (t_seq, t_par)
    assert len(par.results) == 8
    # same winner as sequential (same seed, same configs)
    assert best.metric == pytest.approx(
        min(r.metric for r in par.results))


def test_parallel_trials_pin_devices():
    """Each wave slot gets a distinct device through trial_device."""
    from bigdl_tpu.automl import RandomSearcher, hp, trial_device

    seen = []

    def trial(config):
        with trial_device(config) as dev:
            seen.append(None if dev is None else dev.id)
        return config["x"]

    s = RandomSearcher(mode="min", seed=1)
    s.run(trial, {"x": hp.uniform(0, 1)}, n_sampling=8, parallel=8)
    assert sorted(d for d in seen if d is not None) == list(range(8))


def test_asha_rungs_run_concurrently():
    import time

    from bigdl_tpu.automl import SuccessiveHalvingSearcher, hp

    calls = []

    def trial(config):
        calls.append(config["epochs"])
        time.sleep(0.2)
        return config["lr"] * config["epochs"]

    s = SuccessiveHalvingSearcher(mode="min", seed=0, eta=3, min_budget=1,
                                  max_budget=9)
    t0 = time.perf_counter()
    best = s.run(trial, {"lr": hp.uniform(0.1, 1.0)}, n_sampling=9,
                 parallel=8)
    dt = time.perf_counter() - t0
    # rungs: 9 trials @1 + 3 @3 + 1 @9 = 13 calls; sequential floor would
    # be 13*0.2 = 2.6s — concurrent rungs need ~3 waves (~0.8s)
    assert len(calls) == 13
    assert dt < 1.6, dt
    assert best.config["epochs"] == 9


def test_vmap_sweep_gang_mode():
    """The XLA-native gang: all configs evaluated in one jitted vmap,
    sharded over the mesh; winner matches per-config evaluation."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.automl import hp, vmap_sweep
    from bigdl_tpu.runtime.mesh import MeshSpec, build_mesh

    mesh = build_mesh(MeshSpec(data=8))
    target = 0.3

    def trial(config):
        # quadratic bowl in (lr, wd) — pure jax fn of traced numeric leaves
        return (config["lr"] - target) ** 2 + (config["wd"] - 0.01) ** 2

    # spy on the REAL device_put vmap_sweep issues: the trial sharding
    # must actually SPREAD over the devices (a size-1 outer axis like
    # dcn_data/pipe would park every trial on device 0)
    from jax.sharding import NamedSharding

    seen_shardings = []
    real_put = jax.device_put

    def spy(x, sharding=None, **kw):
        if isinstance(sharding, NamedSharding):
            seen_shardings.append(sharding)
        return real_put(x, sharding, **kw)

    import unittest.mock as mock

    with mock.patch.object(jax, "device_put", spy):
        best_cfg, best_metric, metrics = vmap_sweep(
            trial, {"lr": hp.uniform(0.0, 1.0), "wd": hp.uniform(0.0, 0.1)},
            n_sampling=32, mode="min", seed=3, mesh=mesh)
    assert metrics.shape == (32,)
    assert seen_shardings, "vmap_sweep no longer shards its trial batch"
    probe = real_put(jnp.zeros((32,)), seen_shardings[0])
    assert len(probe.sharding.device_set) == 8
    # matches evaluating each config individually
    per = [float((c["lr"] - target) ** 2 + (c["wd"] - 0.01) ** 2)
           for c in ([best_cfg])]
    assert best_metric == pytest.approx(per[0], rel=1e-5)
    assert best_metric == pytest.approx(float(metrics.min()))
    # Choice axes are rejected with a clear error
    with pytest.raises(ValueError):
        vmap_sweep(trial, {"lr": hp.choice([0.1, 0.2])}, n_sampling=4)
