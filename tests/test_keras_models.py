"""Keras API + model zoo specs."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import keras, models, nn

KEY = jax.random.PRNGKey(0)


def synthetic(n=512, d=16, classes=4, seed=0):
    rng = np.random.RandomState(seed)
    centers = rng.randn(classes, d) * 3
    y = rng.randint(0, classes, size=n)
    x = (centers[y] + rng.randn(n, d)).astype(np.float32)
    return x, y.astype(np.int32)


class TestKerasSequential:
    def test_compile_fit_evaluate_predict(self):
        x, y = synthetic()
        model = keras.Sequential([
            keras.Dense(16, 32), keras.Activation("relu"),
            keras.Dense(32, 4),
        ])
        model.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
                      metrics=["accuracy"])
        model.fit(x[:448], y[:448], batch_size=64, nb_epoch=6,
                  validation_data=(x[448:], y[448:]), log_every=100)
        res = model.evaluate(x[448:], y[448:])
        assert res[0].result > 0.9
        preds = model.predict(x[:8])
        assert preds.shape == (8, 4)


class TestFunctionalModel:
    def test_two_branch_graph(self):
        x, y = synthetic()
        inp = keras.Input(shape=(16,))
        a = keras.Dense(16, 32)(inp)
        a = keras.Activation("relu")(a)
        b = keras.Dense(16, 32)(inp)
        merged = nn.CAddTable()([a, b])
        out = keras.Dense(32, 4)(merged)
        model = keras.Model(inp, out)
        model.compile(optimizer="adam",
                      loss="sparse_categorical_crossentropy",
                      metrics=["accuracy"])
        model.fit(x, y, batch_size=64, nb_epoch=5, log_every=100)
        res = model.evaluate(x, y)
        assert res[0].result > 0.9


class TestZooShapes:
    """Forward-shape specs for every zoo model (tiny inputs)."""

    def test_lenet(self):
        m = models.LeNet5()
        x = jnp.zeros((2, 28, 28, 1))
        v = m.init(KEY, x)
        assert m(v, x).shape == (2, 10)

    def test_resnet_cifar(self):
        m = models.resnet_cifar(depth=8)
        x = jnp.zeros((2, 32, 32, 3))
        v = m.init(KEY, x)
        y, st = m.apply(v, x, training=True)
        assert y.shape == (2, 10)
        assert st  # BN state updated

    @pytest.mark.slow
    def test_resnet50_tiny_input(self):
        m = models.resnet50(classes=10)
        x = jnp.zeros((1, 64, 64, 3))
        v = m.init(KEY, x)
        assert m(v, x).shape == (1, 10)

    @pytest.mark.slow
    def test_inception_v1(self):
        m = models.inception_v1(classes=10)
        x = jnp.zeros((1, 64, 64, 3))
        v = m.init(KEY, x)
        assert m(v, x).shape == (1, 10)

    @pytest.mark.slow
    def test_vgg_cifar(self):
        m = models.vgg_cifar10()
        x = jnp.zeros((1, 32, 32, 3))
        v = m.init(KEY, x)
        assert m(v, x).shape == (1, 10)

    def test_char_rnn(self):
        m = models.char_rnn(vocab_size=20, embed_dim=8, hidden=16)
        x = jnp.zeros((2, 7), jnp.int32)
        v = m.init(KEY, x)
        y = m(v, x)
        assert y.shape == (2, 7, 20)
        np.testing.assert_allclose(np.asarray(jnp.exp(y).sum(-1)), 1.0,
                                   atol=1e-4)

    def test_seq2seq(self):
        m = models.Seq2Seq(input_dim=6, hidden=12, output_len=5, output_dim=3)
        x = jnp.zeros((2, 9, 6))
        v = m.init(KEY, x)
        assert m(v, x).shape == (2, 5, 3)

    def test_transformer_encoder(self):
        m = models.TransformerEncoder(vocab_size=30, hidden=16, layers=2,
                                      heads=2, num_classes=3)
        x = jnp.zeros((2, 11), jnp.int32)
        v = m.init(KEY, x)
        assert m(v, x).shape == (2, 3)

    def test_bert_classifier(self):
        bert = models.BERT(vocab_size=30, hidden=16, layers=2, heads=2)
        m = models.BERTClassifier(bert, num_classes=2)
        x = jnp.zeros((2, 9), jnp.int32)
        v = m.init(KEY, x)
        assert m(v, x).shape == (2, 2)

    def test_autoencoder(self):
        m = models.autoencoder(input_dim=64, hidden=8)
        x = jnp.zeros((2, 64))
        v = m.init(KEY, x)
        assert m(v, x).shape == (2, 64)


class TestZooTraining:
    def test_lenet_trains_on_synthetic_mnist(self):
        """Convergence smoke — the LeNet/MNIST milestone on synthetic digits
        (class = which quadrant has high intensity)."""
        rng = np.random.RandomState(0)
        n = 512
        y = rng.randint(0, 4, n)
        x = rng.rand(n, 28, 28, 1).astype(np.float32) * 0.1
        for i in range(n):
            qi, qj = divmod(y[i], 2)
            x[i, qi * 14:(qi + 1) * 14, qj * 14:(qj + 1) * 14] += 0.8
        from bigdl_tpu import optim
        from bigdl_tpu.data import ArrayDataSet

        model = models.LeNet5(class_num=4)
        opt = optim.Optimizer(model, ArrayDataSet(x, y),
                              nn.ClassNLLCriterion(), batch_size=64)
        opt.set_optim_method(optim.Adam(1e-3))
        opt.set_end_when(optim.Trigger.max_epoch(4))
        opt.log_every = 100
        trained = opt.optimize()
        res = trained.evaluate(ArrayDataSet(x, y), [optim.Top1Accuracy()])
        assert res[0].result > 0.95, res


def test_fit_accepts_epochs_alias():
    from bigdl_tpu import keras as K

    inp = K.Input((6,))
    model = K.Model(inp, K.Dense(2)(inp))
    model.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    x = np.random.RandomState(0).randn(32, 6).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 2, 32)
    model.fit(x, y, batch_size=16, epochs=1, log_every=100)
    assert model.predict(x[:3]).shape == (3, 2)


@pytest.mark.slow
def test_inception_v2_builds_and_forwards():
    import jax

    from bigdl_tpu.models import inception_v2

    model = inception_v2(classes=10)
    x = np.random.RandomState(0).rand(1, 64, 64, 3).astype(np.float32)
    v = model.init(jax.random.PRNGKey(0), x)
    y, _ = model.apply(v, x)
    assert np.asarray(y).shape == (1, 10)
    # log-probs sum to 1
    np.testing.assert_allclose(np.exp(np.asarray(y)).sum(), 1.0, rtol=1e-3)


def test_evaluate_multiinput_without_labels_raises():
    """evaluate() on a multi-input model with y=None must raise — a 2-tuple
    input pack would otherwise be silently unpacked as (data, labels)."""
    import pytest
    from bigdl_tpu.keras.engine import Input, Model
    from bigdl_tpu.keras.layers import Merge
    from bigdl_tpu import nn
    from bigdl_tpu.nn.criterion import CrossEntropyCriterion
    from bigdl_tpu.optim.optim_method import Adam

    ia, ib = Input((4,)), Input((4,))
    out = nn.Linear(8, 2)(Merge("concat")([ia, ib]))
    m = Model([ia, ib], out)
    a = np.random.RandomState(0).rand(16, 4).astype(np.float32)
    b = np.random.RandomState(1).rand(16, 4).astype(np.float32)
    y = np.random.RandomState(2).randint(0, 2, 16).astype(np.int32)
    m.compile(Adam(1e-2), CrossEntropyCriterion())
    m.fit([a, b], y, batch_size=8, nb_epoch=1)
    with pytest.raises(ValueError, match="requires"):
        m.evaluate([a, b])


def test_keras_fit_seq_parallel():
    """model.fit(..., seq_parallel=True) trains a long-context model over
    the (data, seq) mesh through the keras surface."""
    from bigdl_tpu.keras.engine import Input, Model
    from bigdl_tpu.nn.attention import TransformerLayer
    from bigdl_tpu.nn.layers import Linear
    from bigdl_tpu.runtime.engine import Engine, EngineConfig, init_engine
    from bigdl_tpu.runtime.mesh import MeshSpec

    Engine.reset()
    init_engine(EngineConfig(mesh=MeshSpec(data=2, seq=4)))
    rs = np.random.RandomState(0)
    x = rs.randn(64, 16, 8).astype(np.float32)
    y = np.roll(x, 1, axis=1).astype(np.float32)

    inp = Input((16, 8))
    h = TransformerLayer(8, 4, dropout=0.0, causal=True,
                         seq_parallel="ulysses")(inp)
    out = Linear(8, 8)(h)
    model = Model(inp, out)
    model.compile("adam", "mse")
    trained = model.fit(x, y, batch_size=16, epochs=3, log_every=100,
                        seq_parallel=True)
    pred = trained.predict(x[:16])
    assert pred.shape == (16, 16, 8)
    Engine.reset()
