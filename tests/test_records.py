"""Native record-file loader specs (the cached-RDD[Sample] storage analog:
mmap fixed records + threaded gather in native/bigdl_tpu_io.cpp)."""

import os

import numpy as np
import pytest

from bigdl_tpu.data.records import RecordDataSet, write_records
from bigdl_tpu.native import lib as nat

RS = np.random.RandomState(0)


@pytest.fixture
def rec(tmp_path):
    x = RS.rand(100, 4, 4, 3).astype(np.float32)
    y = RS.randint(0, 5, 100).astype(np.int32)
    p = str(tmp_path / "train.btrec")
    write_records(p, {"x": x, "y": y})
    return p, x, y


def test_roundtrip_and_shuffle(rec):
    p, x, y = rec
    ds = RecordDataSet(p)
    assert ds.size() == 100
    gx = np.concatenate([mb["input"] for mb in ds.batches(20, shuffle=False)])
    gy = np.concatenate([mb["target"] for mb in ds.batches(20, shuffle=False)])
    np.testing.assert_array_equal(gx, x)
    np.testing.assert_array_equal(gy, y)
    # shuffled epoch is a permutation, deterministic per (seed, epoch)
    a1 = np.concatenate([mb["target"]
                         for mb in ds.batches(20, seed=3, epoch=1)])
    a2 = np.concatenate([mb["target"]
                         for mb in ds.batches(20, seed=3, epoch=1)])
    np.testing.assert_array_equal(a1, a2)
    assert not np.array_equal(a1, y)
    np.testing.assert_array_equal(np.sort(a1), np.sort(y))
    ds.close()


def test_matches_array_dataset_sharding(rec):
    """Per-process batches equal ArrayDataSet's (same index plan)."""
    from bigdl_tpu.data.dataset import ArrayDataSet

    p, x, y = rec
    ds = RecordDataSet(p)
    ads = ArrayDataSet(x, y)
    for pid in (0, 1):
        got = list(ds.batches(32, shuffle=True, seed=5, process_id=pid,
                              process_count=2))
        want = list(ads.batches(32, shuffle=True, seed=5, process_id=pid,
                                process_count=2))
        assert len(got) == len(want)
        for g, w in zip(got, want):
            np.testing.assert_array_equal(g["input"], w["input"])
            np.testing.assert_array_equal(g["target"], w["target"])
    ds.close()


def test_trains_through_optimizer(rec, tmp_path):
    """RecordDataSet feeds the distributed Optimizer end to end."""
    import jax

    from bigdl_tpu import nn, optim
    from bigdl_tpu.nn.module import Sequential

    n, classes = 200, 3
    x = RS.rand(n, 6).astype(np.float32)
    y = (x[:, 0] > 0.5).astype(np.int32)
    p = str(tmp_path / "clf.btrec")
    write_records(p, {"x": x, "y": y})
    ds = RecordDataSet(p)
    model = Sequential([nn.Linear(6, 16), nn.ReLU(), nn.Linear(16, 2)])
    opt = optim.Optimizer(model, ds, nn.CrossEntropyCriterion(),
                          batch_size=40)
    opt.set_optim_method(optim.Adam(learning_rate=0.02))
    opt.set_end_when(optim.Trigger.max_epoch(20))
    trained = opt.optimize()
    res = trained.evaluate(ds, [optim.Top1Accuracy()], 40)
    assert res[0].result > 0.85, res
    ds.close()


def test_bad_fields_raise(tmp_path):
    with pytest.raises(ValueError):
        write_records(str(tmp_path / "b.btrec"),
                      {"x": np.zeros((3, 2)), "y": np.zeros(4)})
    x = np.zeros((4, 2), np.float32)
    p = str(tmp_path / "ok.btrec")
    write_records(p, {"x": x})
    with pytest.raises(ValueError):
        RecordDataSet(p, feature="nope")


@pytest.mark.skipif(not nat.available(), reason="native lib unavailable")
def test_native_reader_direct(rec):
    p, x, y = rec
    r = nat.RecordReader(p)
    assert r.count() == 100
    raw = r.gather(np.asarray([0, 7, 99], np.int64))
    assert raw.shape == (3, r.record_bytes())
    xb = raw[:, :x[0].nbytes].view(np.float32).reshape(3, 4, 4, 3)
    np.testing.assert_array_equal(xb, x[[0, 7, 99]])
    r.close()
    with pytest.raises(ValueError):
        nat.RecordReader(p + ".json")   # not a record file


def test_multi_field_feature_pack(tmp_path):
    """feature=[a, b] yields tuple inputs (the multi-input convention)."""
    a = RS.rand(20, 3).astype(np.float32)
    b = RS.randint(0, 9, (20, 2)).astype(np.int32)
    y = RS.rand(20).astype(np.float32)
    p = str(tmp_path / "multi.btrec")
    write_records(p, {"a": a, "b": b, "y": y})
    ds = RecordDataSet(p, feature=["a", "b"], label="y")
    mb = next(ds.batches(10, shuffle=False))
    xa, xb = mb["input"]
    np.testing.assert_array_equal(xa, a[:10])
    np.testing.assert_array_equal(xb, b[:10])
    np.testing.assert_array_equal(mb["target"], y[:10])
    ds.close()


def test_thread_prefetch_overlap_and_errors():
    import time

    from bigdl_tpu.data.prefetch import thread_prefetch

    def slow_producer():
        for i in range(4):
            time.sleep(0.05)
            yield i

    t0 = time.time()
    out = []
    for b in thread_prefetch(slow_producer(), depth=2):
        time.sleep(0.05)          # consumer work overlaps producer work
        out.append(b)
    dt = time.time() - t0
    assert out == [0, 1, 2, 3]
    assert dt < 0.35, dt          # sequential would be ~0.4s

    def bad():
        yield 1
        raise RuntimeError("producer boom")

    it = thread_prefetch(bad(), depth=2)
    assert next(it) == 1
    with pytest.raises(RuntimeError, match="boom"):
        list(it)

    with pytest.raises(ValueError):
        list(thread_prefetch(iter([1]), depth=0))


def test_optimizer_with_host_prefetch(rec):
    """host_prefetch=2 trains correctly from a record file (producer runs a
    thread ahead of the device dispatch loop)."""
    import jax

    from bigdl_tpu import nn, optim

    p, x, y = rec
    ds = RecordDataSet(p)
    model = nn.Sequential([nn.Flatten(), nn.Linear(48, 5)])
    opt = optim.Optimizer(model, ds, nn.CrossEntropyCriterion(),
                          batch_size=40)
    opt.host_prefetch = 2
    opt.set_optim_method(optim.Adam(learning_rate=0.05))
    opt.set_end_when(optim.Trigger.max_epoch(3))
    trained = opt.optimize()
    assert trained is not None
    ds.close()


def test_measure_loader_smoke():
    """bench_loader's measurement helper stays importable and returns the
    documented fields (tiny geometry — the artifact run uses batch 768)."""
    import sys

    sys.path.insert(0, ".")
    from bench_loader import measure_loader

    r = measure_loader(batch=16, n_batches=1, src_hw=40, out_hw=32)
    assert r["batch"] == 16 and "host_cores" in r
    assert "python_ref_img_per_sec" in r
    if r["native_available"]:
        assert r["loader_img_per_sec"] > 0


def test_thread_prefetch_abandoned_consumer_stops_producer():
    """ADVICE r3: abandoning the generator (preemption break / end_when /
    exception mid-epoch) must stop the producer thread, not leak it
    blocked on q.put forever."""
    import threading
    import time

    from bigdl_tpu.data.prefetch import thread_prefetch

    closed = []

    def producer():
        try:
            i = 0
            while True:
                yield i
                i += 1
        finally:
            closed.append(True)

    it = thread_prefetch(producer(), depth=1)
    assert next(it) == 0
    it.close()  # consumer abandons mid-stream
    deadline = time.time() + 5.0
    while time.time() < deadline:
        if not any(t.name == "bigdl-tpu-prefetch" and t.is_alive()
                   for t in threading.enumerate()) and closed:
            break
        time.sleep(0.05)
    assert closed, "upstream iterator was not closed"
    assert not any(t.name == "bigdl-tpu-prefetch" and t.is_alive()
                   for t in threading.enumerate()), "producer thread leaked"


@pytest.mark.skipif(not nat.available(), reason="native lib unavailable")
def test_stale_sidecar_rejected(rec, tmp_path):
    """ADVICE r3: a sidecar whose n_records/record_bytes disagree with the
    native header must be rejected (it drives the gather strides)."""
    import json

    p, x, y = rec
    with open(p + ".json") as f:
        manifest = json.load(f)
    manifest["n_records"] = 10_000  # stale/mismatched sidecar
    with open(p + ".json", "w") as f:
        json.dump(manifest, f)
    with pytest.raises(ValueError, match="does not match record header"):
        RecordDataSet(p)


@pytest.mark.skipif(not nat.available(), reason="native lib unavailable")
def test_overflow_header_rejected(tmp_path):
    """ADVICE r3: record_bytes * n_records wrapping uint64 must not pass the
    native bounds check (2**32 * 2**32 == 0 mod 2**64)."""
    import struct

    p = str(tmp_path / "evil.btrec")
    with open(p, "wb") as f:
        f.write(b"BTRECv1\0")
        f.write(struct.pack("<QQ", 2 ** 32, 2 ** 32))
        f.write(b"\0" * 64)
    with pytest.raises(ValueError, match="not a BTRECv1 record file"):
        nat.RecordReader(p)


@pytest.mark.skipif(not nat.available(), reason="native lib unavailable")
def test_zero_record_bytes_rejected(tmp_path):
    import struct

    p = str(tmp_path / "zero.btrec")
    with open(p, "wb") as f:
        f.write(b"BTRECv1\0")
        f.write(struct.pack("<QQ", 0, 5))
        f.write(b"\0" * 64)
    with pytest.raises(ValueError, match="not a BTRECv1 record file"):
        nat.RecordReader(p)


def test_stale_sidecar_rejected_numpy_fallback(rec, monkeypatch):
    """The memmap fallback must apply the same sidecar/header cross-check
    as the native reader."""
    import json

    p, x, y = rec
    with open(p + ".json") as f:
        manifest = json.load(f)
    manifest["n_records"] = 10_000
    with open(p + ".json", "w") as f:
        json.dump(manifest, f)
    monkeypatch.setattr(nat, "available", lambda: False)
    with pytest.raises(ValueError, match="does not match record header"):
        RecordDataSet(p)


def test_process_local_dataset_batching():
    """ProcessLocalDataSet: no double process-sharding, agreed batch
    count, divisibility contract."""
    from bigdl_tpu.data.dataset import ArrayDataSet, ProcessLocalDataSet

    x = np.arange(40, dtype=np.float32).reshape(40, 1)
    y = np.arange(40, dtype=np.int32)
    ds = ProcessLocalDataSet(ArrayDataSet(x, y))
    assert ds.size() == 40
    # process_count=2 halves the per-host batch but does NOT slice rows:
    # this process's local rows all flow through
    got = np.concatenate([mb["input"] for mb in ds.batches(
        8, shuffle=False, process_id=0, process_count=2)])
    np.testing.assert_array_equal(got.ravel(), x.ravel())
    # agreed count: 40 rows / 4-per-host -> 10 batches
    n = sum(1 for _ in ds.batches(8, shuffle=False, process_count=2))
    assert n == 10
    with pytest.raises(ValueError, match="not divisible"):
        list(ds.batches(7, process_count=2))
