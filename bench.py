"""Benchmark harness — prints ONE JSON line.

Metric: ResNet-50 ImageNet-shape training throughput (images/sec/chip) on the
available accelerator — the north-star metric family from BASELINE.json
("ResNet-50 images/sec/chip"). ``vs_baseline`` is reported against the
BASELINE.json published numbers when present; the reference published no
numbers (``published: {}``), so the ratio is against a fixed nominal target
recorded here.
"""

import json
import time

import numpy as np

# Nominal single-chip target for ResNet-50 train throughput. The reference
# publishes no numbers (BASELINE.json "published": {}); papers report CPU-
# cluster figures not comparable per-chip. We pin a TPU-class target so the
# ratio is stable across rounds: v5e-chip-class ResNet-50 training ~ 1000
# img/s/chip order of magnitude.
BASELINE_IMG_PER_SEC_PER_CHIP = 1000.0


def main():
    import os

    import jax

    from bigdl_tpu.runtime.engine import enable_compile_cache

    enable_compile_cache(os.path.join(
        os.path.dirname(os.path.abspath(__file__)), ".jax_cache"))

    import jax.numpy as jnp

    from bigdl_tpu.models.resnet import resnet50
    from bigdl_tpu.nn.criterion import CrossEntropyCriterion
    from bigdl_tpu.optim.optim_method import SGD
    from bigdl_tpu.optim.train_step import ShardedParameterStep
    from bigdl_tpu.runtime.mesh import MeshSpec, build_mesh

    devices = jax.devices()
    on_tpu = devices[0].platform == "tpu"
    n_chips = len(devices)
    mesh = build_mesh(MeshSpec(data=n_chips), devices=devices)

    if on_tpu:
        # batch 768/chip: measured knee of the throughput curve on v5e-class
        # chips (128→2.6k, 256→5.3k, 512→9.6k, 768→12.1k img/s/chip); large
        # per-chip batch keeps the MXU systolic array full
        batch_per_chip, hw, steps = 768, 224, 10
    else:  # CPU smoke fallback so bench.py always emits a line
        batch_per_chip, hw, steps = 4, 64, 3

    batch = batch_per_chip * n_chips
    model = resnet50(classes=1000)
    rng = jax.random.PRNGKey(0)
    x = np.random.RandomState(0).rand(batch, hw, hw, 3).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 1000, (batch,)).astype(np.int32)
    variables = model.init(rng, jnp.asarray(x[:1]))

    step = ShardedParameterStep(
        model, CrossEntropyCriterion(),
        SGD(learning_rate=0.1, momentum=0.9, weight_decay=1e-4), mesh, variables)

    # device-resident batch (steady-state input is overlapped by the
    # prefetch pipeline in real training — bench measures the step engine)
    x_dev = step.shard_batch(x)
    y_dev = step.shard_batch(y)

    # warmup / compile
    loss = step.train_step_device(0, rng, x_dev, y_dev)
    float(np.asarray(loss))  # value fetch, not just ready-handle

    t0 = time.perf_counter()
    for i in range(steps):
        loss = step.train_step_device(i + 1, rng, x_dev, y_dev)
    # fetch the VALUE of the final loss: it is data-dependent on every
    # step in the chain, so the proxied backend cannot acknowledge early
    # the way a bare block_until_ready handle can over the tunnel
    final = float(np.asarray(loss))
    dt = time.perf_counter() - t0
    assert np.isfinite(final), final

    img_per_sec_chip = batch * steps / dt / n_chips
    print(json.dumps({
        "metric": "resnet50_train_throughput"
                  + ("" if on_tpu else "_cpu_smoke"),
        "value": round(img_per_sec_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(img_per_sec_chip / BASELINE_IMG_PER_SEC_PER_CHIP,
                             4),
    }))


if __name__ == "__main__":
    main()
