"""Benchmark harness — prints ONE JSON line (the LAST line of stdout).

Metric: ResNet-50 ImageNet-shape training throughput (images/sec/chip), the
north-star metric family from BASELINE.json ("ResNet-50 images/sec/chip").
``vs_baseline`` is reported against a fixed nominal target recorded here (the
reference published no numbers — BASELINE.json ``published: {}``).

Robustness (round-1 lesson: the TPU backend init can fail *or hang*, and a
round without a parsed JSON line is a round with zero perf evidence):

- the default invocation is an ORCHESTRATOR: it runs the real bench in a
  subprocess (``--worker tpu``) under a bounded timeout, and on failure or
  timeout falls back to a CPU smoke subprocess (``--worker cpu``), annotating
  the JSON with an ``"error"`` field.  The last stdout line is ALWAYS one
  JSON object with ``metric/value/unit/vs_baseline``.
- the TPU worker reports an MFU accounting next to the throughput number:
  FLOPs/step from XLA's own cost analysis of the compiled train step
  (analytic ResNet-50 fallback), and the chip's bf16 peak from device_kind.

Env knobs: ``BENCH_TPU_TIMEOUT`` (s, default 1800 — first ResNet-50 compile
over the tunnel takes minutes; later runs hit ``.jax_cache``),
``BENCH_CPU_TIMEOUT`` (s, default 900), ``BENCH_SWEEP=1`` adds a per-chip
batch-size sweep to the TPU worker JSON (extra compiles).
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

# Nominal single-chip target for ResNet-50 train throughput. The reference
# publishes no numbers (BASELINE.json "published": {}); papers report CPU-
# cluster figures not comparable per-chip. We pin a TPU-class target so the
# ratio is stable across rounds: v5e-chip-class ResNet-50 training ~ 1000
# img/s/chip order of magnitude.
BASELINE_IMG_PER_SEC_PER_CHIP = 1000.0

# Analytic fallback: ResNet-50 @224 forward ~4.09 GMACs => ~8.2 GFLOPs;
# training (fwd + input-grad + weight-grad) ~3x forward.
_RESNET50_TRAIN_FLOPS_PER_IMAGE = 3 * 2 * 4.09e9


def is_good_row(row) -> bool:
    """ONE definition of 'a trustworthy bench row' (shared with
    chipup.py): not suspect, no error, and a sane MFU."""
    try:
        return (not row.get("suspect") and "error" not in row
                and bool(row.get("mfu")) and 0 < row["mfu"] <= 1)
    except Exception:
        return False


def _peak_flops(device_kind: str):
    """Per-chip bf16 peak — delegates to the obs cost model's table so the
    bench denominator and the live ``train.mfu`` gauge can never disagree.
    (bench_lm.py imports this wrapper.)"""
    from bigdl_tpu.obs.cost import peak_flops

    return peak_flops(device_kind)


def _compiled_flops(step, step_args):
    """FLOPs/step of the compiled train step via XLA cost analysis; None on
    any backend that doesn't expose it."""
    try:
        lowered = step._train.lower(*step_args)
        cost = lowered.compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        flops = float(cost["flops"])
        return flops if flops > 0 else None
    except Exception:
        return None


def _cost_analysis_args(step, rng, x, y):
    """The exact train_step_device arg list (9 args incl. the ema slot and
    the trainable-mask scalar — any mismatch makes lower() fail silently
    into the analytic fallback)."""
    import jax.numpy as jnp

    ema_in = step.ema_flat if step.ema_flat is not None else step._ema_dummy
    return (step.flat_params, ema_in, step.opt_state, step.model_state,
            jnp.asarray(0, jnp.int32), rng,
            step.shard_batch(x), step.shard_batch(y),
            jnp.asarray(1.0, jnp.float32))


_COLLECTIVE_MARKERS = ("all-reduce", "all-gather", "reduce-scatter",
                       "collective-permute", "all-to-all", "allreduce",
                       "allgather", "collective")


def _trace_summary(trace_dir):
    """Condense a jax.profiler xplane trace into the bench row: top-5 op
    names by device time + the collective fraction, so every captured MFU
    number carries its own diagnosis (reference Metrics.scala logged
    compute/aggregate/getWeights splits per iteration).  Best-effort: any
    failure returns {"error": ...} and never sinks the row."""
    import glob

    try:
        from jax.profiler import ProfileData

        paths = sorted(glob.glob(
            os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True))
        if not paths:
            return {"error": "no xplane.pb under " + trace_dir}
        pd = ProfileData.from_file(paths[-1])
        device_planes = [p for p in pd.planes if "/device:" in p.name]
        if not device_planes:
            return {"error": "no /device: plane (CPU-only trace)"}
        per_op = {}
        total_ns = 0.0
        collective_ns = 0.0
        for plane in device_planes:
            for line in plane.lines:
                for ev in line.events:
                    dur = float(ev.duration_ns or 0.0)
                    per_op[ev.name] = per_op.get(ev.name, 0.0) + dur
                    total_ns += dur
                    low = ev.name.lower()
                    if any(m in low for m in _COLLECTIVE_MARKERS):
                        collective_ns += dur
        if total_ns <= 0:
            return {"error": "device planes had zero event time"}
        top = sorted(per_op.items(), key=lambda kv: -kv[1])[:5]
        return {
            "planes": [p.name for p in device_planes],
            "total_device_ms": round(total_ns / 1e6, 3),
            "collective_fraction": round(collective_ns / total_ns, 4),
            "top_ops": [
                {"name": n[:120], "ms": round(ns / 1e6, 3),
                 "fraction": round(ns / total_ns, 4)} for n, ns in top],
        }
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"[:300]}


def _run_bench(platform: str) -> dict:
    """The actual measurement (runs inside a worker subprocess)."""
    import jax

    if platform == "cpu" or os.environ.get("JAX_PLATFORMS") == "cpu":
        # this image's axon plugin ignores the JAX_PLATFORMS env var; the
        # config update is what actually forces CPU (tests/conftest.py).
        # Honoring the env var here too lets the chipup sequence be
        # integration-tested end-to-end on CPU (the '--worker tpu' path
        # then degrades to the CPU smoke instead of hanging on axon init).
        jax.config.update("jax_platforms", "cpu")
    else:
        from bigdl_tpu.runtime.engine import enable_compile_cache

        enable_compile_cache(os.path.join(
            os.path.dirname(os.path.abspath(__file__)), ".jax_cache"))

    import jax.numpy as jnp

    from bigdl_tpu.models.resnet import resnet50
    from bigdl_tpu.nn.criterion import CrossEntropyCriterion
    from bigdl_tpu.optim.optim_method import SGD
    from bigdl_tpu.optim.train_step import ShardedParameterStep
    from bigdl_tpu.runtime.mesh import MeshSpec, build_mesh

    devices = jax.devices()
    on_tpu = devices[0].platform == "tpu"
    n_chips = len(devices)
    # default spec: data fills all devices, and on a multislice pod the
    # auto-detected dcn_data axis makes the step's gradient reduction
    # hierarchical (ICI reduce-scatter, 1/ndev slice over DCN)
    mesh = build_mesh(MeshSpec(), devices=devices)

    if on_tpu:
        # batch 768/chip: knee of the round-1 batch curve (whose absolute
        # numbers are unverified — docs/performance.md); large per-chip
        # batch keeps the MXU systolic array full.  BENCH_BATCH overrides
        # (chipup's quick refresh pins it to the snapshot's promoted batch
        # so a refresh never downgrades the headline config)
        batch_per_chip, hw, steps = (
            int(os.environ.get("BENCH_BATCH", "768")), 224, 10)
    else:  # CPU smoke so bench.py always emits a line
        batch_per_chip, hw, steps = 4, 64, 3

    # s2d: the MXU-friendly space-to-depth stem, mathematically equivalent
    # to the 7x7/s2 conv (pack_stem_kernel parity test) — the MLPerf-style
    # ResNet-on-TPU layout.  BENCH_STEM=conv measures the standard stem.
    stem = os.environ.get("BENCH_STEM", "s2d" if on_tpu else "conv")

    def build_step(batch_per_chip):
        batch = batch_per_chip * n_chips
        model = resnet50(classes=1000, stem=stem)
        rng = jax.random.PRNGKey(0)
        x = np.random.RandomState(0).rand(
            batch, hw, hw, 3).astype(np.float32)
        y = np.random.RandomState(1).randint(
            0, 1000, (batch,)).astype(np.int32)
        variables = model.init(rng, jnp.asarray(x[:1]))
        step = ShardedParameterStep(
            model, CrossEntropyCriterion(),
            SGD(learning_rate=0.1, momentum=0.9, weight_decay=1e-4),
            mesh, variables)
        return step, rng, x, y

    def measure(step, rng, x, y, steps, device_resident=True):
        # device-resident batch measures the step engine (steady-state input
        # is overlapped by the prefetch pipeline in real training)
        x_dev = step.shard_batch(x)
        y_dev = step.shard_batch(y)
        loss = step.train_step_device(0, rng, x_dev, y_dev)
        float(np.asarray(loss))  # warmup: value fetch, not just ready-handle
        t0 = time.perf_counter()
        for i in range(steps):
            if device_resident:
                loss = step.train_step_device(i + 1, rng, x_dev, y_dev)
            else:  # host-fed: pays the host->device transfer each step
                loss = step.train_step(i + 1, rng, x, y)
        # fetch the VALUE of the final loss: it is data-dependent on every
        # step in the chain, so the proxied backend cannot acknowledge early
        # the way a bare block_until_ready handle can over the tunnel
        final = float(np.asarray(loss))
        dt = time.perf_counter() - t0
        assert np.isfinite(final), final
        return x.shape[0] * steps / dt / n_chips, dt / steps

    step, rng, x, y = build_step(batch_per_chip)
    img_per_sec_chip, step_time = measure(step, rng, x, y, steps)
    # host-fed companion: ~26x slower over the tunnel, so it costs real
    # seconds of a scarce chip window — BENCH_HOSTFED=0 skips it (the
    # banking quick pass; bench_e2e.py measures host-fed properly)
    img_per_sec_hostfed = None
    if os.environ.get("BENCH_HOSTFED", "1") != "0":
        img_per_sec_hostfed, _ = measure(
            step, rng, x, y, max(steps // 2, 2), device_resident=False)

    profile = None
    if on_tpu and os.environ.get("BENCH_TRACE") == "1":
        # one profiled window for the step-time breakdown
        # (docs/performance.md §Breakdown): the xplane summary is attached
        # to the row as ``profile`` (top-5 ops, collective fraction); the
        # full trace stays on disk for tensorboard/xprof.  Never sinks the
        # bench row.
        try:
            trace_dir = os.path.join(
                os.path.dirname(os.path.abspath(__file__)), "profile_r05")
            with jax.profiler.trace(trace_dir):
                measure(step, rng, x, y, 3)
            profile = _trace_summary(trace_dir)
        except Exception as e:
            profile = {"error": f"{type(e).__name__}: {e}"[:300]}

    # ---- MFU accounting ------------------------------------------------
    flops_per_step = _compiled_flops(
        step, _cost_analysis_args(step, rng, x, y))
    flops_source = "xla_cost_analysis"
    flops_convention_compiled = (
        "compiled-program flops (counts layout/padding math, e.g. the s2d "
        "stem's zero positions) — an upper bound on model flops")
    flops_convention = flops_convention_compiled
    if flops_per_step is not None:
        # cost analysis sees the per-device SPMD module; this row's
        # flops_per_step convention is GLOBAL per step
        flops_per_step *= n_chips
    else:
        flops_per_step = _RESNET50_TRAIN_FLOPS_PER_IMAGE * x.shape[0] \
            * (hw / 224.0) ** 2
        flops_source = "analytic_3x_fwd"
        flops_convention = "model flops (standard-stem ResNet-50 math)"
    peak = _peak_flops(devices[0].device_kind) if on_tpu else None
    achieved = flops_per_step / step_time / n_chips
    mfu = round(achieved / peak, 4) if peak else None

    out = {
        "metric": "resnet50_train_throughput" + ("" if on_tpu else "_cpu_smoke"),
        # live=True marks a fresh measurement from THIS process; the
        # orchestrator's snapshot replay sets it False (advisor r4 medium:
        # downstream consumers must be able to tell replay from live)
        "live": True,
        "value": round(img_per_sec_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(img_per_sec_chip / BASELINE_IMG_PER_SEC_PER_CHIP, 4),
        # the denominator is a pinned nominal target (reference published
        # nothing — BASELINE.json "published": {}), not a measured baseline
        "baseline_source": "nominal",
        "batch_per_chip": batch_per_chip,
        "image_size": hw,
        "stem": stem,
        "steps": steps,
        "n_chips": n_chips,
        "device_kind": devices[0].device_kind,
        "step_time_ms": round(step_time * 1e3, 2),
        "img_per_sec_chip_hostfed": (round(img_per_sec_hostfed, 2)
                                     if img_per_sec_hostfed is not None
                                     else None),
        "flops_per_step": flops_per_step,
        "flops_source": flops_source,
        "flops_convention": flops_convention,
        "achieved_flops_per_chip": round(achieved, 2),
        "peak_bf16_flops": peak,
        "mfu": mfu,
    }
    if profile is not None:
        out["profile"] = profile
    if mfu is not None and mfu > 1.0:
        # >100% model-flop utilization is physically impossible: either the
        # device_kind→peak mapping is wrong (e.g. misrecorded hardware) or
        # the measurement is — flag the row rather than publishing it
        out["suspect"] = True

    if on_tpu:
        # host input-pipeline sustain rate next to the device number
        # (SURVEY §8 hard part #2): loader_img_per_sec * host_cores is the
        # budget; if it can't cover value, training is input-bound host-fed
        try:
            from bench_loader import measure_loader

            out["loader"] = measure_loader(batch=batch_per_chip, n_batches=2)
        except Exception as e:  # loader bench must never sink the TPU row
            out["loader"] = {"error": f"{type(e).__name__}: {e}"[:200]}

    if on_tpu and os.environ.get("BENCH_SWEEP") == "1":
        sweep = {str(batch_per_chip): round(img_per_sec_chip, 2)}
        best = (img_per_sec_chip, batch_per_chip, step_time, None)
        # the r04 curve was still rising at 768 — probe above it too; a
        # batch that OOMs (or hits any compile error) just drops out of
        # the sweep rather than sinking the row
        for b in (128, 256, 512, 1024, 1536):
            if b == batch_per_chip:
                continue  # headline batch already measured (BENCH_BATCH
                #           may pin it to a sweep point)
            s2 = r2 = x2 = y2 = None
            try:
                s2, r2, x2, y2 = build_step(b)
                ips, st = measure(s2, r2, x2, y2, steps)
            except Exception as e:
                sweep[str(b)] = f"failed: {type(e).__name__}"
                continue
            finally:
                # drop trial references before the next (bigger) batch
                # compiles — pinning a trial's device buffers + host batch
                # across later trials can OOM the 1024/1536 probes
                s2 = r2 = x2 = y2 = None
            sweep[str(b)] = round(ips, 2)
            if ips > best[0]:
                best = (ips, b, st, True)
        out["batch_sweep_img_per_sec_chip"] = sweep
        if best[3]:
            # promote the best sweep point to the headline (same measure()
            # protocol, so the numbers are directly comparable)
            ips, b, st, _ = best
            out["value"] = round(ips, 2)
            out["vs_baseline"] = round(ips / BASELINE_IMG_PER_SEC_PER_CHIP, 4)
            out["batch_per_chip"] = b
            out["step_time_ms"] = round(st * 1e3, 2)
            out["headline_promoted_from_sweep"] = True
            # hostfed/loader companion fields were measured at the original
            # batch — still flagged; FLOPs now come from a FRESH cost
            # analysis of the promoted batch's own compiled program
            # (advisor r4: no linear-rescale mixing), falling back to the
            # rescale (flagged) only if the fresh lowering fails.
            out["companion_fields_batch"] = batch_per_chip
            # rebuild the winner once for its own cost analysis (trial
            # objects were dropped above; lower+compile hits the caches)
            try:
                s2, r2, x2, y2 = build_step(b)
                f2 = _compiled_flops(s2, _cost_analysis_args(s2, r2, x2, y2))
            except Exception:
                f2 = None
            finally:
                s2 = r2 = x2 = y2 = None
            if f2 is not None:
                out["flops_source"] = "xla_cost_analysis"
                out["flops_convention"] = flops_convention_compiled
                out["flops_per_step"] = f2 * n_chips
                achieved = f2 * n_chips / st / n_chips
            else:
                out["flops_source"] = flops_source + "+linear_batch_scale"
                scale = b * n_chips / x.shape[0]
                out["flops_per_step"] = flops_per_step * scale
                achieved = flops_per_step * scale / st / n_chips
            out["achieved_flops_per_chip"] = round(achieved, 2)
            if peak:
                out["mfu"] = round(achieved / peak, 4)
                if out["mfu"] > 1.0:
                    # re-apply the sanity gate: the promoted number must
                    # honor the same impossible-MFU flag as the original
                    out["suspect"] = True
    return out


def _run_dispatch_bench(steps: int = 512, ks=(1, 2, 4, 8, 32)) -> dict:
    """Dispatch-gap microbench (docs/performance.md §Step bundling): on a
    small-model geometry (step ≤ 10 ms) the per-step cost is dominated by
    HOST work — rebuilding args, re-entering Python, issuing one XLA
    dispatch per step.  Fused multi-step execution amortizes that over K
    steps; this measures per-step wall/dispatch time at several K on the
    default backend and reports the host-overhead reduction.

    ``host_overhead_per_step(K) = wall_per_step(K) − wall_per_step(K_max)``
    — the deepest bundle is the amortized asymptote (device compute plus
    irreducible per-bundle cost), so the difference isolates what the host
    adds per step at shallower K.  The ``--smoke`` CI gate fails when the
    K=8 reduction drops below 3x (a bundling regression)."""
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # axon quirk: the plugin ignores the env var (tests/conftest.py)
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from bigdl_tpu import nn
    from bigdl_tpu.nn.module import Sequential
    from bigdl_tpu.optim.optim_method import SGD
    from bigdl_tpu.optim.train_step import ShardedParameterStep
    from bigdl_tpu.runtime.mesh import MeshSpec, build_mesh

    mesh = build_mesh(MeshSpec())
    rs = np.random.RandomState(0)
    batch, d_in, classes = 64, 32, 8
    x = rs.randn(batch, d_in).astype(np.float32)
    y = rs.randint(0, classes, batch).astype(np.int32)

    def build():
        model = Sequential([nn.Linear(d_in, 64), nn.ReLU(),
                            nn.Linear(64, classes)])
        variables = model.init(jax.random.PRNGKey(0), jnp.asarray(x[:1]))
        step = ShardedParameterStep(model, nn.CrossEntropyCriterion(),
                                    SGD(learning_rate=0.1), mesh, variables)
        step.set_step_seed(1)
        return step

    wall = {}
    dispatch = {}
    for k in ks:
        step = build()  # fresh engine per K: donation chains stay disjoint
        xs = [step.shard_batch(x)] * k
        ys = [step.shard_batch(y)] * k
        lv, _ = step.train_bundle_device(0, xs, ys)  # warmup: compile
        jax.block_until_ready(lv)
        n, disp = 0, 0.0
        t0 = time.perf_counter()
        while n < steps:
            td = time.perf_counter()
            lv, _ = step.train_bundle_device(n, xs, ys)
            disp += time.perf_counter() - td
            n += k
        jax.block_until_ready(lv)
        wall[k] = (time.perf_counter() - t0) / n
        dispatch[k] = disp / n
    asym = wall[max(ks)]
    overhead = {k: max(wall[k] - asym, 0.0) for k in ks}
    eps = 1e-9
    reduction = overhead.get(1, 0.0) / max(overhead.get(8, 0.0), eps)
    return {
        "metric": "train_dispatch_overhead_reduction",
        "value": round(reduction, 2),
        "unit": "x (per-step host overhead, K=1 vs K=8)",
        "live": True,
        "steps": steps,
        "geometry": {"model": f"mlp {d_in}-64-{classes}", "batch": batch,
                     "n_devices": jax.device_count(),
                     "platform": jax.devices()[0].platform},
        "per_step_wall_us": {str(k): round(wall[k] * 1e6, 1) for k in ks},
        "per_step_dispatch_us": {str(k): round(dispatch[k] * 1e6, 1)
                                 for k in ks},
        "asymptote_wall_us": round(asym * 1e6, 1),
        "host_overhead_per_step_us": {str(k): round(overhead[k] * 1e6, 1)
                                      for k in ks},
    }


def _dispatch_main(smoke: bool):
    steps = int(os.environ.get("BENCH_DISPATCH_STEPS",
                               "256" if smoke else "512"))
    row = _run_dispatch_bench(steps=steps,
                              ks=(1, 8, 32) if smoke else (1, 2, 4, 8, 32))
    if smoke and row["value"] < 3.0:
        row["error"] = (f"bundling regression: K=8 host-overhead reduction "
                        f"{row['value']}x < 3x gate")
        print(json.dumps(row))
        sys.exit(1)
    print(json.dumps(row))


def _worker(platform: str):
    print(json.dumps(_run_bench(platform)))


def _spawn(platform: str, timeout: float):
    """Run a worker subprocess; return (parsed_json_or_None, error_or_None)."""
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--worker", platform],
            capture_output=True, text=True, timeout=timeout,
            cwd=os.path.dirname(os.path.abspath(__file__)))
    except subprocess.TimeoutExpired:
        return None, f"{platform} worker timed out after {timeout:.0f}s"
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln.strip()]
    if lines:
        try:
            parsed = json.loads(lines[-1])
            if proc.returncode == 0:
                return parsed, None
        except json.JSONDecodeError:
            pass
    tail = (proc.stderr or proc.stdout or "").strip().splitlines()[-8:]
    return None, f"{platform} worker rc={proc.returncode}: " + " | ".join(tail)


def _probe_tpu(timeout: float):
    """Short backend-init probe: with the tunnel down, init hangs — don't
    spend the full BENCH_TPU_TIMEOUT discovering that.  Returns
    (ok, error_or_None)."""
    src = ("import jax; d = jax.devices()[0]; "
           "print('PLATFORM=' + d.platform)")
    try:
        proc = subprocess.run([sys.executable, "-c", src],
                              capture_output=True, text=True,
                              timeout=timeout)
    except subprocess.TimeoutExpired:
        return False, f"tpu probe timed out after {timeout:.0f}s (backend init hang)"
    if proc.returncode == 0 and "PLATFORM=tpu" in proc.stdout:
        return True, None
    tail = (proc.stderr or proc.stdout or "").strip().splitlines()[-3:]
    return False, f"tpu probe rc={proc.returncode}: " + " | ".join(tail)


def main():
    tpu_timeout = float(os.environ.get("BENCH_TPU_TIMEOUT", "1800"))
    cpu_timeout = float(os.environ.get("BENCH_CPU_TIMEOUT", "900"))
    probe_timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT", "150"))

    ok, probe_err = _probe_tpu(probe_timeout)
    if ok:
        result, tpu_err = _spawn("tpu", tpu_timeout)
        if result is not None and str(result.get("metric", "")).endswith(
                "_cpu_smoke"):
            # JAX_PLATFORMS=cpu in the env silently degrades the tpu
            # worker to the CPU smoke (the worker honors the var for
            # testability; the probe's bare jax.devices() ignores it —
            # axon quirk).  That row must not pass as a TPU measurement:
            # treat it as a failed attempt so the snapshot replay runs.
            result, tpu_err = None, (
                "tpu worker degraded to cpu smoke (JAX_PLATFORMS=cpu set)")
    else:
        result, tpu_err = None, probe_err
    if result is None and os.environ.get("BENCH_SNAPSHOT_FALLBACK", "1") != "0":
        # live TPU attempt failed: the round's number of record may already
        # have been captured during a chip-up window this session
        # (chipup.py snapshot).  Reporting THAT row (with provenance) beats
        # reporting a CPU smoke — the flaky tunnel must not erase a real
        # measurement taken hours earlier.  The driver overwrites
        # BENCH_r{N}.json with this stdout at round end, so this replay
        # path is what preserves the session's capture; disable with
        # BENCH_SNAPSHOT_FALLBACK=0.  Replayed rows carry live=false
        # (advisor r4 medium) so consumers can always tell.
        here = os.path.dirname(os.path.abspath(__file__))
        candidates = [
            (os.path.join(here, "BENCH_r05.json"), "session snapshot"),
            # if no chip window opened THIS round, the previous round's
            # real measurement (clearly labeled) still beats a CPU smoke
            (os.path.join(here, "BENCH_r04.json"),
             "previous-round snapshot (r04)"),
        ]
        for snap_path, label in candidates:
            try:
                with open(snap_path) as f:
                    snap = json.load(f)
            except Exception:
                continue
            if isinstance(snap, dict) and "parsed" in snap \
                    and isinstance(snap["parsed"], dict):
                # the round driver re-wraps artifacts as
                # {n, cmd, rc, tail, parsed} at round end — unwrap
                snap = snap["parsed"]
            if is_good_row(snap):
                snap["live"] = False
                snap["source"] = (label + " "
                                  + str(snap.get("captured_ts", "unknown")))
                snap["live_attempt"] = f"tpu unavailable ({tpu_err})"
                result = snap
                break
    if result is None:
        result, cpu_err = _spawn("cpu", cpu_timeout)
        if result is not None:
            result["error"] = f"tpu unavailable ({tpu_err}); cpu smoke fallback"
        else:
            result = {
                "metric": "resnet50_train_throughput",
                "value": 0.0,
                "unit": "images/sec/chip",
                "vs_baseline": 0.0,
                "error": f"tpu: {tpu_err}; cpu: {cpu_err}",
            }
    print(json.dumps(result))


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--worker":
        _worker(sys.argv[2])
    elif len(sys.argv) >= 2 and sys.argv[1] in ("--dispatch", "--smoke"):
        # dispatch-gap microbench; --smoke is the CI bundling-regression
        # gate (exit 1 when the K=8 host-overhead reduction < 3x)
        _dispatch_main(smoke=sys.argv[1] == "--smoke")
    else:
        main()
