"""Encoder-decoder Transformer on a toy translation task — the reference's
``nn/Transformer.scala`` WMT configuration (BASELINE.json Seq2Seq config),
TPU-natively: one jitted train step, sharded data-parallel over the mesh,
weight-tied embedding, causal decoder with cross-attention.

Task: "translate" a token sequence to its REVERSE (teacher-forced).  Tiny
but exercises the full encoder-decoder path end to end.

Run: ``python examples/transformer_translation.py``
"""

import _sim_mesh  # noqa: F401  (must be first: simulated-mesh default)

import jax

import jax.numpy as jnp
import numpy as np

from bigdl_tpu.nn import Transformer
from bigdl_tpu.nn.criterion import CrossEntropyCriterion

BOS = 1


def main():
    rs = np.random.RandomState(0)
    vocab, t, n = 32, 8, 512
    src = rs.randint(2, vocab, (n, t)).astype(np.int32)
    tgt = src[:, ::-1].copy()                       # target = reversed source
    tgt_in = np.concatenate([np.full((n, 1), BOS, np.int32),
                             tgt[:, :-1]], axis=1)  # teacher forcing

    model = Transformer(vocab, hidden_size=32, num_heads=4, num_layers=2,
                        dropout=0.0)
    rng = jax.random.PRNGKey(0)
    variables = model.init(rng, src[:2], tgt_in[:2])
    params = variables["params"]
    crit = CrossEntropyCriterion()

    from bigdl_tpu.optim.optim_method import Adam

    method = Adam(learning_rate=2e-3)
    opt_state = method.init_state(params)

    @jax.jit
    def step(i, params, opt_state, src_b, tgt_in_b, tgt_b):
        def loss_fn(p):
            logits, _ = model.forward(p, {}, src_b, tgt_in_b)
            return crit(logits.reshape(-1, vocab), tgt_b.reshape(-1))

        loss, grads = jax.value_and_grad(loss_fn)(params)
        params, opt_state = method.update(i, grads, params, opt_state)
        return params, opt_state, loss

    bs, it = 64, 0
    # not tiny-scaled: the accuracy assert needs the full schedule (240
    # steps on a 32-hidden model is already CI-cheap)
    for epoch in range(30):
        for i in range(0, n, bs):
            params, opt_state, loss = step(
                it, params, opt_state, src[i:i + bs], tgt_in[i:i + bs],
                tgt[i:i + bs])
            it += 1
        print(f"epoch {epoch}: loss {float(loss):.4f}")

    # greedy decode a few sequences
    logits, _ = model.forward(params, {}, src[:4], tgt_in[:4])
    pred = np.asarray(jnp.argmax(logits, -1))
    acc = (pred == tgt[:4]).mean()
    print(f"teacher-forced token accuracy: {acc:.2f}")
    assert acc > 0.9, acc
    print("src[0]     :", src[0].tolist())
    print("reversed[0]:", pred[0].tolist())


if __name__ == "__main__":
    main()
