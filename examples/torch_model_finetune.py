"""Fine-tune a STOCK torch model on the TPU mesh — Orca's headline
capability (``Estimator.from_torch``), TPU-natively.

The torch module never runs on the hot path: its fx graph is converted once
to an NHWC keras-engine model (weights carried over), training runs the
ZeRO-1 sharded step, and the trained weights export straight back into the
original torch module's ``state_dict``.

Run: ``python examples/torch_model_finetune.py``
(CPU: forces an 8-virtual-device mesh; on a TPU host it uses the chips.)
"""

import _sim_mesh  # noqa: F401  (must be first: simulated-mesh default)

import numpy as np
import torch

from bigdl_tpu.estimator import Estimator, init_context
from bigdl_tpu.optim.validation import Top1Accuracy


class Net(torch.nn.Module):
    """A torchvision-style CNN, written with zero knowledge of JAX."""

    def __init__(self, classes=10):
        super().__init__()
        self.conv1 = torch.nn.Conv2d(3, 16, 3, padding=1)
        self.bn1 = torch.nn.BatchNorm2d(16)
        self.conv2 = torch.nn.Conv2d(16, 16, 3, padding=1)
        self.pool = torch.nn.MaxPool2d(2)
        self.head = torch.nn.Linear(16 * 8 * 8, classes)

    def forward(self, x):
        y = torch.relu(self.bn1(self.conv1(x)))
        y = y + torch.relu(self.conv2(y))
        y = self.pool(y)
        return self.head(torch.flatten(y, 1))


def main():
    init_context("local")
    rs = np.random.RandomState(0)
    x = rs.rand(1024, 3, 16, 16).astype(np.float32)   # torch NCHW
    y = (x.mean(axis=(1, 2, 3)) * 20).astype(np.int32) % 10

    est = Estimator.from_torch(
        model_creator=lambda cfg: Net(),
        optimizer_creator=lambda model, cfg: torch.optim.Adam(
            model.parameters(), lr=cfg["lr"]),
        loss_creator=lambda cfg: torch.nn.CrossEntropyLoss(),
        config={"lr": 3e-3},
        example_input=x[:1])

    x_nhwc = x.transpose(0, 2, 3, 1)   # converted model is channels-last
    est.fit((x_nhwc, y), epochs=_sim_mesh.tiny_int(10, 2),
            batch_size=128)
    acc = est.evaluate((x_nhwc, y), [Top1Accuracy()])["Top1Accuracy"]
    print(f"top-1 after fine-tune: {acc:.3f}")

    # trained weights flow back into the ORIGINAL torch module
    tm = Net()
    tm.load_state_dict(est.state_dict())
    tm.eval()
    with torch.no_grad():
        t_acc = (tm(torch.tensor(x[:256])).argmax(1).numpy()
                 == y[:256]).mean()
    print(f"same weights in torch:  {t_acc:.3f}")


if __name__ == "__main__":
    main()
