"""Interop pipeline: train -> export TF GraphDef -> re-import -> IR-fuse ->
int8-quantize -> serve over the dynamic-batching engine.

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 python examples/imported_model_pipeline.py
"""

import _sim_mesh  # noqa: F401  (must be first: simulated-mesh default)

import os
import tempfile


import jax
import numpy as np

from bigdl_tpu import nn, optim
from bigdl_tpu.data.dataset import ArrayDataSet
from bigdl_tpu.nano.inference import InferenceOptimizer
from bigdl_tpu.nn.module import Sequential
from bigdl_tpu.serving import InferenceModel, InputQueue, OutputQueue, ServingServer
from bigdl_tpu.utils.intermediate import IRGraph
from bigdl_tpu.utils.tfio import load_tf_graph, save_tf_graph


def main():
    rng = np.random.RandomState(0)
    x = rng.randn(512, 16, 16, 3).astype(np.float32)
    y = (x.mean(axis=(1, 2, 3)) > 0).astype(np.int32)

    model = Sequential([
        nn.Conv2D(3, 8, 3, padding="SAME"), nn.BatchNorm(8), nn.ReLU(),
        nn.MaxPool2D(2), nn.Flatten(), nn.Linear(8 * 8 * 8, 2),
    ])
    opt = optim.Optimizer(model, ArrayDataSet(x, y),
                          nn.CrossEntropyCriterion(), batch_size=64)
    opt.set_optim_method(optim.Adam(learning_rate=1e-2))
    opt.set_end_when(optim.Trigger.max_epoch(_sim_mesh.tiny_int(3, 1)))
    trained = opt.optimize()

    # 1. export the trained model as a frozen TF GraphDef and re-import it
    pb = os.path.join(tempfile.mkdtemp(), "model.pb")
    save_tf_graph(model, trained.variables, sample=x[:4], path=pb)
    imported, ivars = load_tf_graph(pb)
    print("re-imported graph:", os.path.getsize(pb), "bytes,",
          sum(1 for n in imported.order if n.layer is not None), "layers")

    # 2. IR-retarget to the fused inference engine (BN folded into convs)
    fused, fvars = IRGraph.from_model(imported, ivars).to_model("fused")

    # 3. benchmark fp32 vs bf16 vs int8 variants, pick the best
    res = InferenceOptimizer.optimize(fused, fvars, x[:64],
                                      methods=("fp32", "bf16", "int8"))
    print(res.summary())

    # 4. serve the fused model with dynamic batching
    server = ServingServer(InferenceModel(fused, fvars)).start()
    rid = InputQueue(server).enqueue("req-1", t=x[:8])
    out = OutputQueue(server).query(rid)
    print("served prediction:", np.argmax(out, -1))
    server.stop()


if __name__ == "__main__":
    main()
