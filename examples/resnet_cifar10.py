"""ResNet-20/CIFAR-10 training — reference ``models/resnet/TrainCIFAR10.scala``
(unverified — mount empty): SGD with warmup+multistep schedule, L2 weight
decay, per-epoch validation.

    python examples/resnet_cifar10.py [--epochs 10] [--batch 256]

Synthetic CIFAR-shaped data keeps the example runnable offline; swap
``synthetic_cifar`` for a real loader to train for real.
"""

import _sim_mesh  # noqa: F401  (must be first: simulated-mesh default)

import argparse

import numpy as np

from bigdl_tpu.data.dataset import ArrayDataSet
from bigdl_tpu.models import resnet_cifar
from bigdl_tpu.nn.criterion import CrossEntropyCriterion
from bigdl_tpu.optim import (Optimizer, SGD, Top1Accuracy, Trigger)
from bigdl_tpu.optim.schedules import MultiStep, Warmup, SequentialSchedule
from bigdl_tpu.runtime.engine import init_engine


def synthetic_cifar(n=4096, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.rand(n, 32, 32, 3).astype(np.float32) * 0.3
    y = rs.randint(0, 10, n).astype(np.int32)
    for i, k in enumerate(y):
        x[i, :, :, k % 3] += 0.1 * (k + 1) / 10.0
        x[i, (k * 3) % 28:(k * 3) % 28 + 4, :, :] += 0.4
    return x, y


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int,
                    default=_sim_mesh.tiny_int(10, 1))
    ap.add_argument("--batch", type=int,
                    default=_sim_mesh.tiny_int(256, 128))
    ap.add_argument("--depth", type=int,
                    default=_sim_mesh.tiny_int(20, 8))
    ap.add_argument("--int8", action="store_true",
                    help="after training, int8-quantize (per-channel "
                         "calibration) and check top-1 within 1 pt")
    args = ap.parse_args()

    init_engine()
    x, y = synthetic_cifar(n=_sim_mesh.tiny_int(4096, 1024))
    n_val = len(x) // 8
    train = ArrayDataSet(x[n_val:], y[n_val:])
    val = ArrayDataSet(x[:n_val], y[:n_val])

    steps_per_epoch = (len(x) - n_val) // args.batch
    # linear warmup for one epoch, then step decay at 50%/75% of training
    schedule = (SequentialSchedule()
                .add(Warmup(0.1 / max(steps_per_epoch, 1)), steps_per_epoch)
                .add(MultiStep([steps_per_epoch * (args.epochs // 2),
                                steps_per_epoch * (3 * args.epochs // 4)],
                               0.1), 10 ** 9))
    model = resnet_cifar(depth=args.depth, classes=10)
    opt = (Optimizer(model, train, CrossEntropyCriterion(),
                     batch_size=args.batch)
           .set_optim_method(SGD(learning_rate=0.1, momentum=0.9,
                                 weight_decay=5e-4, nesterov=True,
                                 learning_rate_schedule=schedule))
           .set_end_when(Trigger.max_epoch(args.epochs))
           .set_validation(Trigger.every_epoch(), val, [Top1Accuracy()]))
    trained = opt.optimize()
    res = trained.evaluate(val, [Top1Accuracy()], batch_size=args.batch)
    print("final:", res)

    if args.int8:
        # post-training int8 (reference Quantizer.quantize analog):
        # per-channel calibrated activations + per-out-channel weights
        from bigdl_tpu.nn.quantized import calibrate, quantize

        xv, yv = x[:n_val], y[:n_val]
        calib = calibrate(model, trained.variables,
                          [x[n_val:n_val + 512]], method="percentile",
                          granularity="channel")
        qm, qv = quantize(model, trained.variables, calib=calib)
        # batched: a single 512-image forward would im2col ~500k patch
        # rows per conv in interpret mode on the CPU sim
        preds = []
        for i in range(0, len(xv), args.batch):
            out, _ = qm.forward(qv["params"], qv["state"],
                                xv[i:i + args.batch], training=False)
            preds.append(np.asarray(out).argmax(1))
        acc8 = float((np.concatenate(preds) == yv).mean())
        accf = float(res[0].result)
        print(f"int8 top-1 {acc8:.4f} vs fp32 {accf:.4f} "
              f"(drop {accf - acc8:+.4f})")
        assert accf - acc8 <= 0.01, "int8 dropped more than 1 pt"


if __name__ == "__main__":
    main()
