"""Two-process distributed training demo on CPU — the multi-controller
bootstrap path (one process per TPU-VM host in production; two local CPU
processes here, exactly the reference's ``local-cluster`` Spark test mode).

Reference analog: SURVEY.md §4.3 — Orca's barrier-stage rendezvous →
``torch.distributed.init_process_group``; here the rendezvous is
``jax.distributed.initialize`` driven by the BIGDL_TPU_* env contract that
``Engine`` reads, and gradient sync is the ZeRO-1 sharded step's XLA
collectives running CROSS-PROCESS.

    python examples/multihost_cpu_demo.py          # parent: spawns 2 workers
"""

import os
import subprocess
import sys

import numpy as np


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def worker():
    import jax

    jax.config.update("jax_platforms", "cpu")

    from bigdl_tpu.data.dataset import ArrayDataSet
    from bigdl_tpu import nn
    from bigdl_tpu.nn.criterion import MSECriterion
    from bigdl_tpu.optim import Optimizer, SGD, Trigger
    from bigdl_tpu.runtime.engine import init_engine

    init_engine()  # reads BIGDL_TPU_COORDINATOR/NUM_PROCESSES/PROCESS_ID
    pid = jax.process_index()
    print(f"[worker {pid}] sees {jax.device_count()} global devices, "
          f"{jax.local_device_count()} local", flush=True)

    # identical data on every process (the DataSet shards by process_id)
    rs = np.random.RandomState(0)
    w_true = np.asarray([[2.0], [-1.0], [0.5], [3.0]], np.float32)
    x = rs.rand(256, 4).astype(np.float32)
    y = x @ w_true

    model = nn.Linear(4, 1)
    opt = (Optimizer(model, ArrayDataSet(x, y), MSECriterion(),
                     batch_size=64)
           .set_optim_method(SGD(learning_rate=0.3))
           # not tiny-scaled: the convergence assert needs the full 30
           # epochs, and the tiny linear model makes them near-free
           .set_end_when(Trigger.max_epoch(30)))
    trained = opt.optimize()

    w = np.asarray(trained.variables["params"]["weight"])
    err = float(np.abs(w - w_true).max())
    print(f"[worker {pid}] weight err {err:.5f}", flush=True)
    assert err < 0.05, err
    print(f"[worker {pid}] OK", flush=True)


def main():
    if os.environ.get("BIGDL_TPU_COORDINATOR"):
        worker()
        return
    nproc = 2
    port = int(os.environ.get("DEMO_PORT", "0")) or _free_port()
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pythonpath = os.pathsep.join(
        p for p in [repo_root, os.environ.get("PYTHONPATH")] if p)
    procs = []
    try:
        for r in range(nproc):
            env = dict(os.environ,
                       BIGDL_TPU_COORDINATOR=f"127.0.0.1:{port}",
                       BIGDL_TPU_NUM_PROCESSES=str(nproc),
                       BIGDL_TPU_PROCESS_ID=str(r),
                       JAX_PLATFORMS="cpu",
                       PYTHONPATH=pythonpath)
            env.pop("XLA_FLAGS", None)  # one device per process
            procs.append(subprocess.Popen([sys.executable, __file__], env=env))
        codes = [p.wait(timeout=600) for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    if any(codes):
        raise SystemExit(f"worker exit codes: {codes}")
    print("multihost demo: both workers converged")


if __name__ == "__main__":
    main()
