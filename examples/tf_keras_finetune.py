"""Fine-tune a STOCK tf.keras model on the TPU mesh — the Orca TF2
Estimator capability (``Estimator.from_keras``), TPU-natively.

TensorFlow never runs on the hot path: the Keras-3 layer graph converts
once to the native keras-engine model (weights carried over, keras
optimizer/loss mapped to native equivalents), training runs the ZeRO-1
sharded step, and the trained weights export straight back into the
original keras model with ``export_to_keras()``.

Run: ``python examples/tf_keras_finetune.py``
(CPU: forces an 8-virtual-device mesh; on a TPU host it uses the chips.)
"""

import _sim_mesh  # noqa: F401  (must be first: simulated-mesh default)

import jax

import numpy as np

from bigdl_tpu.estimator import Estimator, init_context
from bigdl_tpu.optim.validation import Top1Accuracy


def model_creator(config):
    """A plain tf.keras model, written with zero knowledge of JAX."""
    from tensorflow import keras as tk

    tk.utils.set_random_seed(0)
    m = tk.Sequential([
        tk.layers.Input((16, 16, 3)),
        tk.layers.Conv2D(16, 3, padding="same", activation="relu"),
        tk.layers.BatchNormalization(),
        tk.layers.MaxPooling2D(2),
        tk.layers.Conv2D(32, 3, padding="same", activation="relu"),
        tk.layers.GlobalAveragePooling2D(),
        tk.layers.Dense(config.get("classes", 4)),
    ])
    m.compile(optimizer=tk.optimizers.Adam(config.get("lr", 3e-3)),
              loss=tk.losses.SparseCategoricalCrossentropy(from_logits=True))
    return m


def main():
    init_context("local")
    rs = np.random.RandomState(0)
    n, classes = 512, 4
    x = rs.rand(n, 16, 16, 3).astype(np.float32)
    y = (x.mean(axis=(1, 2, 3)) * 13).astype(np.int32) % classes

    est = Estimator.from_keras(model_creator, config={"classes": classes})
    before = est.evaluate((x, y), [Top1Accuracy()])["Top1Accuracy"]
    est.fit((x, y), epochs=_sim_mesh.tiny_int(10, 1), batch_size=64)
    after = est.evaluate((x, y), [Top1Accuracy()])["Top1Accuracy"]
    print(f"accuracy {before:.2f} -> {after:.2f} on {jax.device_count()} "
          "devices")

    # trained weights flow back into the ORIGINAL keras model
    km = est.export_to_keras()
    theirs = km.predict(x[:4], verbose=0).argmax(-1)
    ours = np.asarray(est.predict(x[:4])).argmax(-1)
    assert (theirs == ours).all()
    print("keras round-trip predictions agree:", theirs.tolist())


if __name__ == "__main__":
    main()
