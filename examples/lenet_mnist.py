"""LeNet-5 training — the reference's canonical first example.

Reference analog: ``dllib/models/lenet/Train.scala`` (unverified — mount
empty): Engine.init → DataSet → Optimizer(model, dataset, criterion) →
setValidation/setCheckpoint → optimize.

Runs on whatever devices are present (1 TPU chip, or
``XLA_FLAGS=--xla_force_host_platform_device_count=8`` CPU mesh).  Uses the
real MNIST if an IDX file path is given, else a synthetic digit-like set so
the example is runnable offline.

    python examples/lenet_mnist.py [--epochs 5] [--batch 256]
"""

import _sim_mesh  # noqa: F401  (must be first: simulated-mesh default)

import argparse

import numpy as np

import jax

from bigdl_tpu.data.dataset import ArrayDataSet
from bigdl_tpu.models import LeNet5
from bigdl_tpu.nn.criterion import CrossEntropyCriterion
from bigdl_tpu.optim import (Adam, Optimizer, Top1Accuracy, Trigger)
from bigdl_tpu.runtime.engine import init_engine


def synthetic_mnist(n=4096, seed=0):
    """Digit-shaped blobs: class k = square at a class-dependent position.
    Learnable to ~100% by LeNet; stands in for MNIST offline."""
    rs = np.random.RandomState(seed)
    x = rs.rand(n, 28, 28, 1).astype(np.float32) * 0.15
    y = rs.randint(0, 10, n).astype(np.int32)
    for i, k in enumerate(y):
        r, c = 2 + (k // 5) * 10, 2 + (k % 5) * 5
        x[i, r:r + 8, c:c + 4, 0] += 0.8
    return x, y


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int,
                    default=_sim_mesh.tiny_int(5, 1))
    ap.add_argument("--batch", type=int,
                    default=_sim_mesh.tiny_int(256, 64))
    args = ap.parse_args()

    init_engine()
    x, y = synthetic_mnist()
    n_val = len(x) // 8
    train = ArrayDataSet(x[n_val:], y[n_val:])
    val = ArrayDataSet(x[:n_val], y[:n_val])

    model = LeNet5(class_num=10)
    opt = (Optimizer(model, train, CrossEntropyCriterion(),
                     batch_size=args.batch)
           .set_optim_method(Adam(learning_rate=1e-3))
           .set_end_when(Trigger.max_epoch(args.epochs))
           .set_validation(Trigger.every_epoch(), val, [Top1Accuracy()]))
    trained = opt.optimize()

    results = trained.evaluate(val, [Top1Accuracy()], batch_size=args.batch)
    print("final:", results)


if __name__ == "__main__":
    main()
