"""Shared example bootstrap: ``import _sim_mesh`` FIRST in every example.

Defaults to the simulated 8-virtual-device CPU mesh — with the TPU tunnel
down, real-backend init hangs, so examples must opt IN to real chips with
``BIGDL_TPU_REAL_CHIPS=1`` ("0"/"false"/empty count as off).

In simulated mode CPU is forced UNCONDITIONALLY: this image exports
``JAX_PLATFORMS=axon`` by default (not a user choice), and the axon plugin
also ignores the env var — so both the env var and ``jax.config`` are set
to cpu (the tests/conftest gotcha).  With real chips opted in, nothing is
touched.
"""

import os


def _on(v: str) -> bool:
    return v.strip().lower() not in ("", "0", "false", "no")


if not _on(os.environ.get("BIGDL_TPU_REAL_CHIPS", "")):
    os.environ.setdefault("XLA_FLAGS",
                          "--xla_force_host_platform_device_count=8")
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.config.update("jax_platforms", "cpu")


def tiny() -> bool:
    """CI tiny-size mode: ``BIGDL_TPU_EXAMPLES_TINY=1`` shrinks every
    example's epochs/steps/data so the whole set runs in minutes (the
    reference's nightly example runs, SURVEY.md §5, scaled for CI)."""
    return _on(os.environ.get("BIGDL_TPU_EXAMPLES_TINY", ""))


def tiny_int(normal: int, small: int) -> int:
    return small if tiny() else normal
