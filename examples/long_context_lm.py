"""Long-context LM training with SEQUENCE PARALLELISM — the production
path for sequences too long for one chip's HBM (a capability the
reference lacks: its Transformer attention is single-device O(L²)).

The mesh carries a "seq" axis; every (batch, seq, ...) tensor is sharded
over it, the model's attention runs the ring (or Ulysses all-to-all)
sequence-parallel kernel, and the standard ZeRO-1 Optimizer drives the
whole thing — `opt.seq_parallel = True` is the only training-loop change.

    python examples/long_context_lm.py [--strategy ring|ulysses]
"""

import _sim_mesh  # noqa: F401  (must be first: simulated-mesh default)

import argparse

import numpy as np

from bigdl_tpu import nn, optim
from bigdl_tpu.data.dataset import ArrayDataSet
from bigdl_tpu.nn.attention import TransformerLayer
from bigdl_tpu.runtime.engine import init_engine


def copy_task(rs, n, L, vocab):
    """Predict token t-1 at position t (needs attention, not pointwise)."""
    x = rs.randint(4, vocab, (n, L)).astype(np.int32)
    y = np.concatenate([np.zeros((n, 1), np.int64), x[:, :-1]], 1)
    return x, y.astype(np.int32)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--strategy", default="ring",
                    choices=["ring", "ulysses"])
    ap.add_argument("--seq-len", type=int,
                    default=_sim_mesh.tiny_int(256, 64))
    ap.add_argument("--epochs", type=int,
                    default=_sim_mesh.tiny_int(40, 30))
    args = ap.parse_args()

    # 2-way data x 4-way sequence parallelism on the 8-device mesh
    init_engine(data=2, seq=4)
    rs = np.random.RandomState(0)
    vocab, d_model, heads = 32, 32, 4
    x, y = copy_task(rs, 256, args.seq_len, vocab)

    model = nn.Sequential([
        nn.LookupTable(vocab, d_model),
        # shard-aware: each sequence block offsets positions by its
        # global block start (a plain PE would restart every block at 0)
        nn.PositionalEncoding(),
        TransformerLayer(d_model, heads, dropout=0.0, causal=True,
                         seq_parallel=args.strategy),
        nn.Linear(d_model, vocab),
    ])
    opt = optim.Optimizer(model, ArrayDataSet(x, y),
                          nn.CrossEntropyCriterion(), batch_size=32)
    opt.set_optim_method(optim.Adam(learning_rate=3e-3))
    opt.set_end_when(optim.Trigger.max_epoch(args.epochs))
    opt.seq_parallel = True
    trained = opt.optimize()

    logits = trained.predict(x[:16])          # (B, L, vocab), seq-sharded fwd
    pred = np.argmax(np.asarray(logits), -1)
    acc = float((pred[:, 1:] == y[:16, 1:]).mean())
    print(f"{args.strategy} seq-parallel next-token acc: {acc:.3f} "
          f"(seq_len {args.seq_len} sharded 4-way)")
    assert acc > 0.9, acc


if __name__ == "__main__":
    main()
