"""Chronos-equivalent TCN forecasting — reference Chronos quickstart shape:
``TSDataset.from_pandas → impute → scale → roll → TCNForecaster.fit``.

    python examples/tcn_forecast.py [--epochs 5]
"""

import _sim_mesh  # noqa: F401  (must be first: simulated-mesh default)

import argparse

import numpy as np
import pandas as pd

from bigdl_tpu.forecast import TCNForecaster, TSDataset


def synthetic_series(n=2000, seed=0):
    rs = np.random.RandomState(seed)
    t = np.arange(n)
    value = (np.sin(2 * np.pi * t / 24) + 0.5 * np.sin(2 * np.pi * t / 168)
             + 0.1 * rs.randn(n))
    return pd.DataFrame({
        "timestamp": pd.date_range("2025-01-01", periods=n, freq="h"),
        "value": value.astype(np.float32),
    })


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int,
                    default=_sim_mesh.tiny_int(5, 1))
    ap.add_argument("--lookback", type=int, default=48)
    ap.add_argument("--horizon", type=int, default=24)
    args = ap.parse_args()

    df = synthetic_series()
    split = int(len(df) * 0.8)
    tr = (TSDataset.from_pandas(df.iloc[:split], dt_col="timestamp",
                                target_col="value")
          .impute().scale()
          .roll(lookback=args.lookback, horizon=args.horizon))
    te = (TSDataset.from_pandas(df.iloc[split:], dt_col="timestamp",
                                target_col="value")
          .impute().scale(tr.scaler, fit=False)
          .roll(lookback=args.lookback, horizon=args.horizon))

    f = TCNForecaster(past_seq_len=args.lookback,
                      future_seq_len=args.horizon,
                      input_feature_num=1, output_feature_num=1)
    f.fit(tr, epochs=args.epochs)
    metrics = f.evaluate(te, metrics=["mae", "mse"])
    print("eval:", metrics)
    pred = f.predict(te)
    print("pred shape:", pred.shape)


if __name__ == "__main__":
    main()
