"""LoRA fine-tune — parameter-efficient adaptation with the base frozen
(beyond the reference; the PEFT pattern on this framework's modules).

Scope: ``apply_lora`` wraps ``Linear`` leaves of Containers and keras
graphs (the zoo's fused attention blocks keep raw projection matrices —
adapters there would need per-matrix hooks, not layer wraps).

The flow fine-tunes a frozen pretrained-style MLP text classifier:
adapters (+nothing else) train with a masked gradient, then merge to a
dense model, then the merged model POST-TRAINING-QUANTIZES to int8 —
the full adapt->merge->serve path.

    python examples/lora_finetune.py [--steps 200]
"""

import _sim_mesh  # noqa: F401  (must be first: simulated-mesh default)

import argparse

import numpy as np

import jax
import jax.numpy as jnp

from bigdl_tpu import nn
from bigdl_tpu.nn.criterion import CrossEntropyCriterion
from bigdl_tpu.nn.lora import apply_lora, lora_filter, merge_lora
from bigdl_tpu.nn.module import Sequential
from bigdl_tpu.nn.quantized import quantize


def bag_of_tokens(n, vocab=512, seed=0):
    """Bag-of-words text classification: class = which of two disjoint
    keyword sets dominates the sentence."""
    rs = np.random.RandomState(seed)
    x = np.zeros((n, vocab), np.float32)
    y = rs.randint(0, 2, n).astype(np.int32)
    for i in range(n):
        words = rs.randint(0, vocab, 32)
        kw = rs.randint(0, 50, 6) + (0 if y[i] == 0 else 50)
        for w in np.concatenate([words, kw]):
            x[i, w] += 1.0
    return x / 8.0, y


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=_sim_mesh.tiny_int(200, 12))
    ap.add_argument("--rank", type=int, default=4)
    args = ap.parse_args()

    x, y = bag_of_tokens(_sim_mesh.tiny_int(1024, 128))
    model = Sequential([nn.Linear(x.shape[1], 128), nn.ReLU(),
                        nn.Linear(128, 64), nn.ReLU(),
                        nn.Linear(64, 2)])
    # "pretrain" briefly on HALF the classes' data distribution, then
    # LoRA-adapt on the full task with the base frozen
    variables = model.init(jax.random.PRNGKey(0), jnp.asarray(x[:2]))

    lmodel, lvars = apply_lora(model, variables, rank=args.rank)
    params = lvars["params"]
    mask = lora_filter(params)
    n_train = sum(int(np.prod(np.shape(l))) for l, m in zip(
        jax.tree_util.tree_leaves(params),
        jax.tree_util.tree_leaves(mask)) if m)
    n_total = sum(int(np.prod(np.shape(l)))
                  for l in jax.tree_util.tree_leaves(params))
    print(f"trainable adapter params: {n_train} / {n_total} "
          f"({100 * n_train / n_total:.1f}%)")
    assert n_train > 0

    crit = CrossEntropyCriterion()
    xb, yb = jnp.asarray(x), jnp.asarray(y)

    @jax.jit
    def step(p):
        def loss_fn(p):
            out, _ = lmodel.forward(p, {}, xb)
            return crit(out, yb)

        l, g = jax.value_and_grad(loss_fn)(p)
        g = jax.tree_util.tree_map(
            lambda gi, mi: gi if mi else jnp.zeros_like(gi), g, mask)
        return jax.tree_util.tree_map(lambda a, b: a - 0.1 * b, p, g), l

    for i in range(args.steps):
        params, loss = step(params)
        if i % 40 == 0:
            print(f"step {i}: loss {float(loss):.4f}")

    lvars = {"params": params, "state": {}}
    dense_model, dense_vars = merge_lora(lmodel, lvars)
    out_l, _ = lmodel.apply(lvars, xb)
    out_d, _ = dense_model.apply(dense_vars, xb)
    acc = float((np.asarray(out_d).argmax(-1) == y).mean())
    drift = float(np.abs(np.asarray(out_l) - np.asarray(out_d)).max())

    # merged dense model quantizes like any other (serve int8)
    q_model, q_vars = quantize(dense_model, dense_vars)
    out_q, _ = q_model.apply(q_vars, xb)
    acc_q = float((np.asarray(out_q).argmax(-1) == y).mean())
    print(f"final: acc {acc:.3f} (int8 {acc_q:.3f}), merged-vs-lora "
          f"max drift {drift:.2e}")
    assert drift < 1e-4
    assert acc > 0.62  # tiny-mode floor; full run trains far higher


if __name__ == "__main__":
    main()
