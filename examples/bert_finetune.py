"""BERT-style classifier fine-tune through the Orca-equivalent Estimator —
the BASELINE.json "Orca BERT-base fine-tune over DataFrames" config shape.

Reference analog: Orca ``Estimator.from_torch`` BERT fine-tune examples
(python/orca, unverified — mount empty).  Here the estimator drives a
bigdl_tpu BERT classifier over the mesh with the ZeRO-1 sharded step.

    python examples/bert_finetune.py [--steps 120]
"""

import _sim_mesh  # noqa: F401  (must be first: simulated-mesh default)

import argparse

import numpy as np

import jax

from bigdl_tpu.data.dataset import ArrayDataSet
from bigdl_tpu.models import BERT, BERTClassifier
from bigdl_tpu.nn.criterion import CrossEntropyCriterion
from bigdl_tpu.optim import AdamWeightDecay, Optimizer, Top1Accuracy, Trigger
from bigdl_tpu.runtime.engine import init_engine


def synthetic_sentences(n=1024, seq=64, vocab=1000, seed=0):
    """Class = whether token 7 appears in the first half — forces real
    attention over positions, not just bag-of-words."""
    rs = np.random.RandomState(seed)
    x = rs.randint(10, vocab, (n, seq)).astype(np.int32)
    y = rs.randint(0, 2, n).astype(np.int32)
    for i in range(n):
        if y[i]:
            x[i, rs.randint(0, seq // 2)] = 7
        else:
            x[i, :seq // 2][x[i, :seq // 2] == 7] = 11
    return x, y


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int,
                    default=_sim_mesh.tiny_int(120, 6))
    ap.add_argument("--batch", type=int,
                    default=_sim_mesh.tiny_int(64, 16))
    args = ap.parse_args()

    init_engine()
    x, y = synthetic_sentences()
    train = ArrayDataSet(x, y)

    bert = BERT(vocab_size=1000, hidden=128, layers=2, heads=4,
                max_position=64)
    model = BERTClassifier(bert, num_classes=2)

    opt = (Optimizer(model, train, CrossEntropyCriterion(),
                     batch_size=args.batch)
           .set_optim_method(AdamWeightDecay(learning_rate=3e-4,
                                             weight_decay=0.01))
           .set_end_when(Trigger.max_iteration(args.steps)))
    trained = opt.optimize()
    print("final:", trained.evaluate(train, [Top1Accuracy()],
                                     batch_size=args.batch))


if __name__ == "__main__":
    main()
