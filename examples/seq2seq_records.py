"""End-to-end Seq2Seq: record-file input → encoder-decoder Transformer →
KV-cached decode.

Integrates three round-3 subsystems: the native mmap record loader
(``data/records.py``; samples never have to fit in Python RAM), the WMT-
style ``nn.Transformer`` (translation mode, weight-tied embedding), and
``transformer_decode_cached`` (per-layer KV caches at inference).

Task: translate a token sequence to its reverse.  Run:
``python examples/seq2seq_records.py``
"""

import _sim_mesh  # noqa: F401  (must be first: simulated-mesh default)
import os
import tempfile


import jax

import numpy as np

from bigdl_tpu.data.records import RecordDataSet, write_records
from bigdl_tpu.nn import Transformer
from bigdl_tpu.nn.attention import transformer_decode_cached
from bigdl_tpu.nn.criterion import CrossEntropyCriterion
from bigdl_tpu.optim.optim_method import Adam

BOS, EOS = 1, 0


def main():
    rs = np.random.RandomState(0)
    vocab, t, n = 24, 6, 1024
    src = rs.randint(2, vocab, (n, t)).astype(np.int32)
    tgt = np.concatenate([src[:, ::-1], np.full((n, 1), EOS, np.int32)], 1)
    tgt_in = np.concatenate([np.full((n, 1), BOS, np.int32),
                             tgt[:, :-1]], 1)

    # pack the corpus into ONE record file; training reads it back through
    # the native mmap gather (no full-dataset array resident in the loop)
    tmp = tempfile.TemporaryDirectory()
    path = os.path.join(tmp.name, "wmt_toy.btrec")
    write_records(path, {"src": src, "tgt_in": tgt_in, "tgt": tgt})
    ds = RecordDataSet(path, feature=["src", "tgt_in"], label="tgt")
    print(f"record file: {os.path.getsize(path) / 1e3:.0f} kB, "
          f"{ds.size()} samples")

    model = Transformer(vocab, hidden_size=32, num_heads=4, num_layers=2,
                        dropout=0.0)
    variables = model.init(jax.random.PRNGKey(0), src[:2], tgt_in[:2])
    params = variables["params"]
    crit = CrossEntropyCriterion()
    method = Adam(learning_rate=2e-3)
    opt_state = method.init_state(params)

    @jax.jit
    def step(i, params, opt_state, src_b, tgt_in_b, tgt_b):
        def loss_fn(p):
            logits, _ = model.forward(p, {}, src_b, tgt_in_b)
            return crit(logits.reshape(-1, vocab), tgt_b.reshape(-1))

        loss, grads = jax.value_and_grad(loss_fn)(params)
        params, opt_state = method.update(i, grads, params, opt_state)
        return params, opt_state, loss

    it = 0
    # not tiny-scaled: the decode-accuracy assert needs the full schedule
    # (25 epochs x 8 steps on a 32-hidden model is already CI-cheap)
    for epoch in range(25):
        for mb in ds.batches(128, shuffle=True, seed=0, epoch=epoch):
            src_b, tgt_in_b = mb["input"]          # multi-field record pack
            params, opt_state, loss = step(
                it, params, opt_state, src_b, tgt_in_b, mb["target"])
            it += 1
        if epoch % 5 == 4:
            print(f"epoch {epoch}: loss {float(loss):.4f}")
    ds.close()
    tmp.cleanup()

    # KV-cached greedy decode — O(L) attention per generated token
    tokens, _ = transformer_decode_cached(model, params, src[:4], BOS, EOS,
                                          max_len=t + 1)
    pred = np.asarray(tokens)[:, 1:t + 1]
    acc = (pred == src[:4, ::-1]).mean()
    print(f"decode token accuracy: {acc:.2f}")
    assert acc > 0.9, acc
    print("src[0]    :", src[0].tolist())
    print("decoded[0]:", pred[0].tolist())


if __name__ == "__main__":
    main()
