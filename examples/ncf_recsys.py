"""NCF recommendation with Friesian-style feature prep and HR@10/NDCG@10
evaluation — the BigDL NCF headline workload shape.

    python examples/ncf_recsys.py [--steps 200]
"""

import _sim_mesh  # noqa: F401  (must be first: simulated-mesh default)

import argparse

import numpy as np
import jax
import jax.numpy as jnp

from bigdl_tpu.models import NeuralCF
from bigdl_tpu.nn.criterion import BCEWithLogitsCriterion
from bigdl_tpu.optim.optim_method import Adam
from bigdl_tpu.optim.validation import HitRatio, NDCG
from bigdl_tpu.runtime.engine import init_engine


def synthetic_interactions(users=200, items=500, per_user=20, seed=0):
    """Latent-factor ground truth: user/item embeddings whose dot product
    drives interaction probability."""
    rs = np.random.RandomState(seed)
    pu = rs.randn(users, 8) * 0.7
    qi = rs.randn(items, 8) * 0.7
    u = np.repeat(np.arange(users), per_user)
    i = rs.randint(0, items, len(u))
    logits = np.sum(pu[u] * qi[i], -1)
    y = (1 / (1 + np.exp(-logits)) > rs.rand(len(u))).astype(np.float32)
    return u.astype(np.int32), i.astype(np.int32), y[:, None], (pu, qi)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int,
                    default=_sim_mesh.tiny_int(800, 30))
    args = ap.parse_args()

    init_engine()
    users, items = 200, 500
    u, i, y, _ = synthetic_interactions(users, items)

    model = NeuralCF(users, items, embed_dim=16, mlp_dims=(32, 16),
                     include_sigmoid=False)  # train on logits (stable BCE)
    v = model.init(jax.random.PRNGKey(0), jnp.asarray(u), jnp.asarray(i))
    crit = BCEWithLogitsCriterion()
    params = v["params"]
    optm = Adam(learning_rate=1e-3)
    ost = optm.init_state(params)

    @jax.jit
    def step(carry, it):
        params, ost = carry

        def loss(p):
            out, _ = model.forward(p, {}, jnp.asarray(u), jnp.asarray(i))
            return crit(out, jnp.asarray(y))

        l, g = jax.value_and_grad(loss)(params)
        params, ost = optm.update(it, g, params, ost)
        return (params, ost), l

    for s in range(args.steps):
        (params, ost), l = step((params, ost), s)
        if s % 100 == 0:
            print(f"step {s}: loss {float(l):.4f}")

    # leave-one-out style eval: for each of 64 users score 1 seen-positive
    # item against 99 random negatives
    rs = np.random.RandomState(1)
    rows = []
    for uu in range(64):
        pos_items = i[(u == uu) & (y[:, 0] == 1)]
        if len(pos_items) == 0:
            continue
        cand = np.concatenate([[pos_items[0]],
                               rs.randint(0, items, 99)]).astype(np.int32)
        uu_rep = np.full(100, uu, np.int32)
        scores, _ = model.forward(params, {}, jnp.asarray(uu_rep),
                                  jnp.asarray(cand))
        rows.append(np.asarray(scores)[:, 0])
    scores = jnp.asarray(np.stack(rows))
    tgt = jnp.zeros((scores.shape[0],), jnp.int32)
    for m in (HitRatio(10), NDCG(10)):
        s, c = m.batch_stats(scores, tgt)
        print(f"{m.name}: {float(s) / float(c):.4f}")


if __name__ == "__main__":
    main()
