"""Text classifier — the reference's TextClassifier app pattern
(word tokenize → vocabulary → embed → conv/pool → dense), run on synthetic
two-topic data through the keras-1 API.

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 python examples/text_classifier.py
"""

import _sim_mesh  # noqa: F401  (must be first: simulated-mesh default)

import numpy as np

from bigdl_tpu import keras as K
from bigdl_tpu.data.text import Vocabulary, pad_to, word_tokenize

SPORTS = ("game team score win play match season league goal coach "
          "ball player field race sprint").split()
TECH = ("code model chip compile tensor kernel graph shard cache "
        "memory device cluster network stack bug").split()
FILLER = "the a of and to in on for with at is was".split()


def make_corpus(rng, n):
    texts, labels = [], []
    for i in range(n):
        topic = SPORTS if i % 2 == 0 else TECH
        words = rng.choice(topic, size=8).tolist() + \
            rng.choice(FILLER, size=8).tolist()
        rng.shuffle(words)
        texts.append(" ".join(words))
        labels.append(i % 2)
    return texts, np.asarray(labels, np.int32)


def main():
    rng = np.random.RandomState(0)
    texts, labels = make_corpus(rng, 512)
    tokens = [word_tokenize(t) for t in texts]
    vocab = Vocabulary.build(tokens)
    seq_len = 16
    x = np.stack([pad_to(vocab.encode(t), seq_len) for t in tokens])

    model = K.Sequential([
        K.Embedding(len(vocab), 32),
        K.Convolution1D(32, 64, 3, padding="SAME"),
        K.Activation("relu"),
        K.Flatten(),
        K.Dense(2),
    ])
    model.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    model.fit(x[:448], labels[:448], batch_size=32,
              epochs=_sim_mesh.tiny_int(5, 1),
              validation_data=(x[448:], labels[448:]))
    pred = model.predict(x[448:])
    acc = (np.argmax(pred, -1) == labels[448:]).mean()
    print(f"holdout accuracy: {acc:.3f}")


if __name__ == "__main__":
    main()
