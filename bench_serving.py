"""Sustained-load serving bench — the SERVING_r*.json evidence source.

Methodology matches the r05 capture (tests/test_serving_multiproc.py):
the engine + HTTP frontend run in a SUBPROCESS (their own GIL, a real
socket boundary) and N client threads drive sustained load from this
process.  Differences from r05, which are the point of the r08 rebuild:

- clients hold keep-alive HTTP/1.1 connections (the proxy does the same
  per worker now — TCP setup is no longer billed to every request);
- the engine runs CONTINUOUS batching by default (``--fixed`` re-runs the
  legacy fixed-window loop on the same geometry for the A/B);
- the server installs the PR 6 recompile sentinel, warms every predict
  bucket, marks steady, and the bench finishes with a MIXED-SIZE request
  sweep — the run fails unless the sweep triggers ZERO unexpected XLA
  recompiles (bucket padding doing its job).

Output: one JSON row on the last stdout line (the sentinel's
``_load_fresh`` contract) with ``throughput_rps`` / ``p50_ms`` /
``p99_ms`` / ``avg_batch_size`` — the families the perf-regression
sentinel gates against the committed SERVING_r* trajectory.

The ``--decode`` mode is the DECODE_r*.json evidence source
(docs/serving.md §Autoregressive decode): a subprocess LM server runs
the token-level continuous decode engine, keep-alive STREAMING clients
drive a sustained mixed prompt/output-length geometry, and the run
reports aggregate + per-user tokens/s, time-to-first-token, and
inter-token p99.  The A/B baseline is the SAME engine with
``continuous=False`` — whole-batch-restart admission (every slot must
free before the next wave starts), which is exactly what the one-scan
whole-batch decode serving amounted to; ``speedup_vs_static`` is the
continuous engine's tokens/s over that baseline and is sentinel-gated
(≥2x on the committed geometry).  The sustained mixed-length load
doubles as the recompile sweep: the run fails unless the server saw
ZERO unexpected XLA recompiles.

CLI::

    python bench_serving.py                  # full sustained-load run
    python bench_serving.py --fixed          # legacy-engine A/B
    python bench_serving.py --smoke          # CI gate: correctness +
                                             # batching + zero recompiles
    python bench_serving.py --decode         # token-level decode bench
    python bench_serving.py --decode --smoke # CI gate for the decode path
    python bench_serving.py --decode --spec  # speculative decode A/B
                                             # (DECODE_SPEC_r*.json)
    python bench_serving.py --fleet          # disaggregated decode fleet
    python bench_serving.py --fleet --smoke  # CI gate for the fleet path
    python bench_serving.py --out SERVING_r08.json

The ``--fleet`` mode is the DECODE_POOL_r*.json evidence source
(docs/serving.md §Decode fleet): a ``ServingPool`` subprocess runs a
dedicated ``role=prefill`` worker plus decode workers, the proxy's
KV-aware router splits every streaming ``/generate`` (prompt KV pages
cross the serialized handoff channel), and the same mixed-geometry
streaming clients as ``--decode`` drive it — so the TTFT p99 row is
directly comparable to the committed single-host DECODE_r* baseline,
against which it is gated at >= 2x better.
"""

import argparse
import http.client
import json
import os
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.abspath(__file__))

# the r05 geometry: Linear(8,16)+ReLU+Linear(16,4), 2-row requests
SERVER = textwrap.dedent("""
    import sys
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")

    from bigdl_tpu import nn
    from bigdl_tpu.obs.attr import recompile_sentinel
    from bigdl_tpu.optim.metrics import global_metrics
    from bigdl_tpu.serving.inference_model import InferenceModel
    from bigdl_tpu.serving.server import ServingConfig, ServingServer
    from bigdl_tpu.serving.http_frontend import HttpFrontend

    sent = recompile_sentinel().install()
    model = nn.Sequential([nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4)])
    variables = model.init(jax.random.PRNGKey(0),
                           np.zeros((1, 8), np.float32))
    im = InferenceModel(model, variables)
    im.warmup(np.zeros((8,), np.float32))   # one compile per bucket
    srv = ServingServer(im, ServingConfig(
        batch_size=%(batch_size)d, batch_timeout_s=%(batch_timeout)s,
        queue_capacity=%(queue_capacity)d,
        continuous=%(continuous)s)).start()
    fe = HttpFrontend(srv, port=0).start()
    probe = np.arange(16, dtype=np.float32).reshape(2, 8) / 16.0
    print("REF=" + json.dumps(im.predict(probe).tolist()), flush=True)
    sent.mark_steady()
    print(f"URL={fe.url}", flush=True)
    sys.stdin.readline()        # parent closes stdin to stop us
    fe.stop(); srv.stop()
    m = global_metrics()
    print("RECOMPILES="
          + str(int(m.counter('train.unexpected_recompiles_total'))),
          flush=True)
    print(f"STATS={srv.stats['batches']},{srv.stats['requests']}",
          flush=True)
""").replace("import sys", "import json\nimport sys", 1)


class _Server:
    """The engine subprocess: URL + REF on start, RECOMPILES/STATS on
    stdin close."""

    def __init__(self, continuous: bool, batch_size: int = 16,
                 batch_timeout_s: float = 0.002,
                 queue_capacity: int = 1024):
        code = SERVER % {"batch_size": batch_size,
                         "batch_timeout": repr(batch_timeout_s),
                         "queue_capacity": queue_capacity,
                         "continuous": repr(continuous)}
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=os.pathsep.join(
            p for p in [REPO, os.environ.get("PYTHONPATH")] if p))
        env.pop("XLA_FLAGS", None)
        self.proc = subprocess.Popen([sys.executable, "-c", code], env=env,
                                     stdin=subprocess.PIPE,
                                     stdout=subprocess.PIPE, text=True)
        self.ref = None
        self.url = None
        deadline = time.time() + 180
        while time.time() < deadline and self.url is None:
            line = self.proc.stdout.readline().strip()
            if line.startswith("REF="):
                self.ref = np.asarray(json.loads(line[4:]), np.float32)
            elif line.startswith("URL="):
                self.url = line[4:]
            elif not line and self.proc.poll() is not None:
                raise RuntimeError("bench server died during startup")
        if self.url is None:
            self.proc.kill()
            raise RuntimeError("bench server never printed its URL")
        host, _, port = self.url.split("//", 1)[1].partition(":")
        self.host, self.port = host, int(port)

    def finish(self) -> dict:
        try:
            if not self.proc.stdin.closed:
                self.proc.stdin.close()
        except OSError:
            pass
        out = self.proc.stdout.read()
        self.proc.wait(timeout=60)
        info = {}
        for line in out.splitlines():
            if line.startswith("RECOMPILES="):
                info["unexpected_recompiles"] = int(line.split("=", 1)[1])
            elif line.startswith("STATS="):
                b, r = line.split("=", 1)[1].split(",")
                info["batches"], info["requests"] = int(b), int(r)
            elif line.startswith("SPEC="):
                d, a, rj = line.split("=", 1)[1].split(",")
                info["spec_drafted"] = int(d)
                info["spec_accepted"] = int(a)
                info["spec_rejected"] = int(rj)
        if "batches" not in info:
            raise RuntimeError(f"bench server exited without stats: {out!r}")
        return info


def _post(host: str, port: int, conn, body: bytes, timeout: float = 30.0,
          decode: bool = True):
    """One keep-alive POST /predict; reconnects once on a stale socket.
    Returns (conn, decoded_json) — or (conn, raw_bytes) with
    ``decode=False``, which keeps client-side JSON work out of the timed
    loop (the bench measures the SERVER, and client CPU competes with it
    on a small box)."""
    for attempt in (0, 1):
        if conn is None:
            conn = http.client.HTTPConnection(host, port, timeout=timeout)
        try:
            conn.request("POST", "/predict", body=body,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            data = resp.read()
        except Exception:
            conn.close()
            conn = None
            if attempt:
                raise
            continue
        if resp.status != 200:
            raise RuntimeError(f"HTTP {resp.status}: {data[:200]!r}")
        return conn, (json.loads(data) if decode else data)
    raise RuntimeError("unreachable")


def _sustained_load(server: _Server, clients: int, duration_s: float):
    """N keep-alive client threads posting the r05-geometry request until
    the deadline; returns (completed, latencies_s, wall_s, errors)."""
    rs = np.random.RandomState(0)
    bodies = [json.dumps({"instances":
                          rs.rand(2, 8).astype(np.float32).tolist()}
                         ).encode() for _ in range(16)]
    lats = [[] for _ in range(clients)]
    errors = []
    start = time.time()
    stop_t = start + duration_s

    def client(ci):
        conn = None
        try:
            i = 0
            while time.time() < stop_t:
                t0 = time.perf_counter()
                conn, raw = _post(server.host, server.port,
                                  conn, bodies[(ci + i) % len(bodies)],
                                  decode=False)
                lats[ci].append(time.perf_counter() - t0)
                if i == 0:   # decode once per client: shape sanity only
                    assert len(json.loads(raw)["predictions"]) == 2
                i += 1
        except Exception as e:  # noqa: BLE001 — reported by the caller
            errors.append(e)
        finally:
            if conn is not None:
                conn.close()

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=duration_s + 120)
    wall = time.time() - start
    flat = np.sort(np.concatenate([np.asarray(x) for x in lats if x]))
    return int(flat.size), flat, wall, errors


def _mixed_size_sweep(server: _Server) -> int:
    """Post one request per odd/over-bucket size: every tail shape the
    bucket padding must absorb without a fresh XLA compile."""
    rs = np.random.RandomState(1)
    n = 0
    conn = None
    for rows in (1, 2, 3, 5, 7, 9, 13, 17, 33, 63, 65, 150, 300):
        body = json.dumps({"instances":
                           rs.rand(rows, 8).astype(np.float32).tolist()}
                          ).encode()
        conn, out = _post(server.host, server.port, conn, body)
        assert len(out["predictions"]) == rows, (
            rows, len(out["predictions"]))
        n += 1
    if conn is not None:
        conn.close()
    return n


def run_bench(continuous: bool, clients: int, duration_s: float) -> dict:
    server = _Server(continuous=continuous)
    try:
        # correctness probe against the server's own reference prediction
        conn, out = _post(server.host, server.port, None, json.dumps(
            {"instances": (np.arange(16, dtype=np.float32)
                           .reshape(2, 8) / 16.0).tolist()}).encode())
        conn.close()
        np.testing.assert_allclose(
            np.asarray(out["predictions"], np.float32), server.ref,
            rtol=1e-5, atol=1e-6)
        # brief warm phase (HTTP handler threads, client sockets) that
        # stays out of the measured window
        _sustained_load(server, clients, min(0.5, duration_s))
        completed, lats, wall, errors = _sustained_load(
            server, clients, duration_s)
        if errors:
            raise RuntimeError(f"{len(errors)} client errors: {errors[0]}")
        swept = _mixed_size_sweep(server)
    finally:
        info = server.finish()
    # engine-side stats cover warmup+probe+sweep too; the occupancy ratio
    # is measured over the whole run — continuous assembly must keep it
    # up across all phases, not just the measured window
    avg_batch = round(info["requests"] / max(info["batches"], 1), 2)
    return {
        "engine": "continuous" if continuous else "fixed",
        # sentinel family scope: same-geometry captures gate each other;
        # the untagged r04/r05 light-load rows stay out of this trajectory
        "geometry": f"sustained_c{clients}",
        "requests": completed,
        "concurrent_clients": clients,
        "duration_s": round(wall, 2),
        "batches": info["batches"],
        "avg_batch_size": avg_batch,
        "occupancy": round(avg_batch / 16.0, 4),
        "throughput_rps": round(completed / wall, 1),
        "p50_ms": round(float(lats[int(0.50 * (lats.size - 1))]) * 1e3, 2),
        "p99_ms": round(float(lats[int(0.99 * (lats.size - 1))]) * 1e3, 2),
        "mixed_size_sweep": swept,
        "unexpected_recompiles": info.get("unexpected_recompiles", -1),
        "keep_alive_clients": True,
    }


def _smoke() -> int:
    """CI gate (seconds-scale, machine-independent): both engines answer
    correctly under concurrent keep-alive load, batching actually
    coalesces, and the mixed-size sweep triggers zero unexpected XLA
    recompiles.  Absolute rps is NOT gated here — that is the committed
    SERVING_r*.json trajectory's job via the perf sentinel."""
    failures = []
    rows = {}
    for continuous in (True, False):
        row = run_bench(continuous, clients=8, duration_s=0.8)
        rows[row["engine"]] = row
        if row["requests"] <= 0:
            failures.append(f"{row['engine']}: no requests completed")
        # avg_batch_size is engine-lifetime requests/batches — the same
        # scope on both sides (the client-side "requests" count covers
        # only the measured window, a mismatched denominator)
        if row["avg_batch_size"] < 1.2:
            failures.append(f"{row['engine']}: batching never coalesced "
                            f"(avg batch {row['avg_batch_size']} under "
                            f"8 concurrent clients)")
        if row["unexpected_recompiles"] != 0:
            failures.append(
                f"{row['engine']}: {row['unexpected_recompiles']} "
                "unexpected XLA recompiles across the mixed-size sweep")
    print(json.dumps({"smoke": "ok" if not failures else "fail",
                      "failures": failures,
                      "continuous_rps": rows["continuous"]["throughput_rps"],
                      "fixed_rps": rows["fixed"]["throughput_rps"],
                      "continuous_avg_batch":
                          rows["continuous"]["avg_batch_size"],
                      "fixed_avg_batch": rows["fixed"]["avg_batch_size"]}))
    return 0 if not failures else 1


# ---------------------------------------------------------------------------
# token-level decode bench (--decode): the DECODE_r*.json evidence source
# ---------------------------------------------------------------------------

# tiny LM geometry: vocab 64, hidden 32, 2 heads, 2 layers; slot pool 8,
# 8-token pages, 64-token cap.  Continuous vs whole-batch-restart rides
# the SAME engine code behind DecodeConfig(continuous=).
DECODE_SERVER = textwrap.dedent("""
    import json
    import sys
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")

    from bigdl_tpu.nn.attention import Transformer
    from bigdl_tpu.obs.attr import recompile_sentinel
    from bigdl_tpu.optim.metrics import global_metrics
    from bigdl_tpu.serving import (DecodeConfig, InferenceModel,
                                   ServingConfig, ServingServer,
                                   SpecConfig)
    from bigdl_tpu.serving.http_frontend import HttpFrontend

    sent = recompile_sentinel().install()
    model = Transformer(vocab_size=64, hidden_size=32, num_heads=2,
                        num_layers=2, dropout=0.0, mode="lm")
    variables = model.init(jax.random.PRNGKey(0),
                           np.arange(8, dtype=np.int32)[None])
    im = InferenceModel(model, variables, decode=DecodeConfig(
        slots=%(slots)d, page_size=8, pages_per_slot=16, prompt_chunk=8,
        max_new_tokens=120, eos_id=1, continuous=%(continuous)s,
        kv_dtype=%(kv_dtype)r, speculative=%(speculative)s),
        weight_quant=%(weight_quant)r)
    im.decode_engine.warmup()
    srv = ServingServer(im, ServingConfig(batch_size=8)).start()
    fe = HttpFrontend(srv, port=0).start()
    sent.mark_steady()
    print(f"URL={fe.url}", flush=True)
    sys.stdin.readline()
    fe.stop(); srv.stop(); im.decode_engine.stop()
    m = global_metrics()
    print("RECOMPILES="
          + str(int(m.counter('train.unexpected_recompiles_total'))),
          flush=True)
    st = im.decode_engine.stats
    print("STATS=%%d,%%d" %% (st['steps'], st['completed']), flush=True)
    print("SPEC=%%d,%%d,%%d" %% (st['spec_drafted'], st['spec_accepted'],
                                 st['spec_rejected']), flush=True)
""")


class _DecodeServer(_Server):
    def __init__(self, continuous: bool, slots: int = 8,
                 kv_dtype: str = "float32", weight_quant=None,
                 speculative: str = "None"):
        code = DECODE_SERVER % {"continuous": repr(continuous),
                                "slots": slots, "kv_dtype": kv_dtype,
                                "weight_quant": weight_quant,
                                "speculative": speculative}
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PYTHONPATH=os.pathsep.join(
                       p for p in [REPO, os.environ.get("PYTHONPATH")]
                       if p))
        env.pop("XLA_FLAGS", None)
        self.proc = subprocess.Popen([sys.executable, "-c", code], env=env,
                                     stdin=subprocess.PIPE,
                                     stdout=subprocess.PIPE, text=True)
        self.ref = None
        self.url = None
        deadline = time.time() + 240
        while time.time() < deadline and self.url is None:
            line = self.proc.stdout.readline().strip()
            if line.startswith("URL="):
                self.url = line[4:]
            elif not line and self.proc.poll() is not None:
                raise RuntimeError("decode bench server died on startup")
        if self.url is None:
            self.proc.kill()
            raise RuntimeError("decode bench server never printed its URL")
        host, _, port = self.url.split("//", 1)[1].partition(":")
        self.host, self.port = host, int(port)


def _decode_request_mix(rs):
    """One request of the mixed geometry: short prompts, short-heavy
    output lengths (85%) with a long tail (15% near the horizon) — the
    production chat regime, and the one where slot recycling beats
    whole-batch restarts hardest (a wave pays the longest member's
    horizon; the mean request is an order of magnitude shorter)."""
    plen = int(rs.randint(4, 17))
    max_new = int(rs.randint(96, 121) if rs.rand() < 0.15
                  else rs.randint(4, 10))
    prompt = rs.randint(2, 64, (plen,)).tolist()
    return prompt, max_new


def _stream_generate(host, port, conn, body, timeout=60.0):
    """One streaming /generate on a persistent keep-alive connection.
    Returns (conn, t_first_token, token_times, n_tokens)."""
    import http.client as _hc

    for attempt in (0, 1):
        if conn is None:
            conn = _hc.HTTPConnection(host, port, timeout=timeout)
        try:
            conn.request("POST", "/generate", body=body,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
        except Exception:
            conn.close()
            conn = None
            if attempt:
                raise
            continue
        if resp.status != 200:
            raise RuntimeError(f"HTTP {resp.status}: "
                               f"{resp.read()[:200]!r}")
        t_first = None
        times = []
        while True:
            line = resp.readline()
            if not line:
                break
            # the bench measures the SERVER; keep client-side JSON work
            # out of the per-token loop (it competes for the same CPU)
            if line.startswith(b'{"token"'):
                times.append(time.time())
                if t_first is None:
                    t_first = times[-1]
                continue
            line = line.strip()
            if not line:
                continue
            event = json.loads(line)
            if event.get("done") or "error" in event:
                if "error" in event:
                    raise RuntimeError(f"generate error: {event}")
                break
        resp.read()   # drain the terminal chunk so the conn is reusable
        return conn, t_first, times
    raise RuntimeError("unreachable")


def _decode_client_threads(host: str, port: int, clients: int,
                           duration_s: float, seed0: int):
    """The thread-level load loop (one process's worth of clients).
    Token RATE accounting is windowed: only tokens that arrived inside
    the ``duration_s`` window count, so in-flight stragglers drained
    after the deadline neither inflate nor dilute tokens/s.  Latency
    samples (TTFT, inter-token gaps) keep every completed request."""
    ttfts, gaps, errors = [], [], []
    in_window = [0]
    lock = threading.Lock()
    start_t = time.time()
    stop_t = start_t + duration_s

    def client(ci):
        rs = np.random.RandomState(seed0 + ci)
        conn = None
        try:
            while time.time() < stop_t:
                prompt, max_new = _decode_request_mix(rs)
                body = json.dumps({"tokens": prompt,
                                   "max_new_tokens": max_new,
                                   "stream": True}).encode()
                t0 = time.time()
                conn, t_first, times = _stream_generate(
                    host, port, conn, body)
                with lock:
                    if t_first is not None:
                        ttfts.append(t_first - t0)
                    gaps.extend(b - a for a, b in zip(times, times[1:]))
                    in_window[0] += sum(1 for t in times if t <= stop_t)
        except Exception as e:  # noqa: BLE001 — reported by caller
            errors.append(e)
        finally:
            if conn is not None:
                conn.close()

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=duration_s + 180)
    return ttfts, gaps, in_window[0], errors


def _decode_worker_main(argv) -> int:
    """``--decode-worker host port threads duration seed`` — one load
    PROCESS.  The aggregate token rate of the continuous engine exceeds
    what one Python process's GIL can consume, so the parent fans the
    client threads out over several of these."""
    host, port, threads, duration, seed = (
        argv[0], int(argv[1]), int(argv[2]), float(argv[3]), int(argv[4]))
    # the load generator is the measuring instrument: at the default 5ms
    # GIL switch interval its own thread scheduling shows up in the TTFT
    # and inter-token tails it reports for the server
    sys.setswitchinterval(0.001)
    ttfts, gaps, tokens, errors = _decode_client_threads(
        host, port, threads, duration, seed)
    print(json.dumps({"ttfts": ttfts, "gaps": gaps, "tokens": tokens,
                      "errors": [str(e) for e in errors[:3]]}))
    return 0


def _decode_load(server, clients: int, duration_s: float):
    """Streaming keep-alive load from several worker PROCESSES (a
    single client process saturates its GIL before the server
    saturates) posting mixed-geometry generate requests."""
    procs = max(1, min(4, clients // 8))
    per = clients // procs
    env = dict(os.environ)
    workers = [subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--decode-worker",
         server.host, str(server.port), str(per), str(duration_s),
         str(1000 + 100 * i)],
        stdout=subprocess.PIPE, text=True, env=env)
        for i in range(procs)]
    ttfts, gaps, errors = [], [], []
    tokens = 0
    for w in workers:
        out, _ = w.communicate(timeout=duration_s + 240)
        row = json.loads(out.strip().splitlines()[-1])
        ttfts.extend(row["ttfts"])
        gaps.extend(row["gaps"])
        tokens += row["tokens"]
        errors.extend(row["errors"])
    # in-window tokens over the nominal window (every worker measures
    # its own); wall returned for the artifact row only
    return ttfts, gaps, [tokens], duration_s, errors


def _pct(xs, q):
    if not xs:
        return 0.0
    xs = np.sort(np.asarray(xs))
    return float(xs[int(q * (xs.size - 1))])


def run_decode_bench(continuous: bool, clients: int,
                     duration_s: float, slots: int = 8,
                     kv_dtype: str = "float32",
                     weight_quant=None,
                     speculative: str = "None") -> dict:
    server = _DecodeServer(continuous=continuous, slots=slots,
                           kv_dtype=kv_dtype, weight_quant=weight_quant,
                           speculative=speculative)
    try:
        # warm phase outside the window: handler threads + client conns
        _decode_load(server, clients, min(0.6, duration_s))
        ttfts, gaps, counts, wall, errors = _decode_load(
            server, clients, duration_s)
        if errors:
            raise RuntimeError(f"{len(errors)} client errors: {errors[0]}")
    finally:
        info = server.finish()
    tokens = int(sum(counts))
    adjud = info.get("spec_accepted", 0) + info.get("spec_rejected", 0)
    return {
        "engine": "continuous" if continuous else "static_batch_restart",
        "spec_drafted": info.get("spec_drafted", 0),
        "spec_accepted": info.get("spec_accepted", 0),
        "spec_accept_rate": (round(info["spec_accepted"] / adjud, 4)
                             if adjud else 0.0),
        "geometry": f"decode_s{slots}_c{clients}",
        "concurrent_clients": clients,
        "duration_s": round(wall, 2),
        "requests": len(ttfts),
        "tokens": tokens,
        "tokens_per_s": round(tokens / wall, 1),
        "tokens_per_s_user": round(tokens / wall / clients, 2),
        "ttft_ms_p50": round(_pct(ttfts, 0.50) * 1e3, 2),
        "ttft_ms_p99": round(_pct(ttfts, 0.99) * 1e3, 2),
        "inter_token_p99_ms": round(_pct(gaps, 0.99) * 1e3, 2),
        "engine_steps": info["batches"],      # STATS first field
        "completed_requests": info["requests"],
        "unexpected_recompiles": info.get("unexpected_recompiles", -1),
        "streaming_clients": True,
    }


def run_decode(clients: int, duration_s: float, out=None,
               smoke: bool = False) -> int:
    """Both arms on the same geometry; the continuous row (plus the
    baseline's tokens/s and the speedup) is the committed artifact."""
    cont = run_decode_bench(True, clients, duration_s)
    static = run_decode_bench(False, clients, duration_s)
    speedup = (round(cont["tokens_per_s"] / static["tokens_per_s"], 2)
               if static["tokens_per_s"] else 0.0)
    row = dict(cont, static_tokens_per_s=static["tokens_per_s"],
               static_ttft_ms_p99=static["ttft_ms_p99"],
               speedup_vs_static=speedup)
    failures = []
    for arm in (cont, static):
        if arm["tokens"] <= 0:
            failures.append(f"{arm['engine']}: no tokens generated")
        if arm["unexpected_recompiles"] != 0:
            failures.append(
                f"{arm['engine']}: {arm['unexpected_recompiles']} "
                "unexpected XLA recompiles under the mixed-length load")
    if not smoke and speedup < 2.0:
        failures.append(f"continuous tokens/s only {speedup}x the "
                        "whole-batch-restart baseline (< 2x)")
    if out:
        with open(out, "w") as f:
            json.dump(row, f, indent=1)
    print(json.dumps(row))
    if failures:
        print("FAIL: " + "; ".join(failures), file=sys.stderr)
        return 1
    return 0


# ---------------------------------------------------------------------------
# quantized decode bench (--decode --quant): the DECODE_QUANT_r*.json
# evidence source (docs/quantization.md §Serving memory hierarchy)
# ---------------------------------------------------------------------------

# Engine-level parity drill run in its own interpreter: builds the SAME
# tiny LM twice — f32 KV + f32 weights vs int8 KV pages + int8 serving
# weights — and greedy-decodes an identical mixed-geometry prompt batch
# through both.  Prints the token-agreement fraction, the per-page HBM
# cost of each KV dtype (the equal-HBM-budget slot math runs on these),
# and the unexpected-recompile counter (both engines warm up BEFORE
# mark_steady, so the int8 programs joining the compile set is expected;
# anything after is not).
QUANT_PARITY = textwrap.dedent("""
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")

    from bigdl_tpu.nn.attention import Transformer
    from bigdl_tpu.obs.attr import recompile_sentinel
    from bigdl_tpu.optim.metrics import global_metrics
    from bigdl_tpu.serving.decode_engine import (DecodeConfig,
                                                 DecodeEngine, LMAdapter)

    sent = recompile_sentinel().install()
    model = Transformer(vocab_size=64, hidden_size=32, num_heads=2,
                        num_layers=2, dropout=0.0, mode="lm")
    params = model.init(jax.random.PRNGKey(0),
                        np.arange(8, dtype=np.int32)[None])["params"]
    rs = np.random.RandomState(0)
    prompts = [rs.randint(2, 64, (int(rs.randint(4, 17)),)).tolist()
               for _ in range(8)]

    def build(kv_dtype, weight_quant):
        cfg = DecodeConfig(slots=4, page_size=8, pages_per_slot=16,
                           prompt_chunk=8, max_new_tokens=32, eos_id=1,
                           kv_dtype=kv_dtype)
        eng = DecodeEngine(LMAdapter(model, params, cap=cfg.cap,
                                     weight_quant=weight_quant), cfg)
        eng.warmup()
        return eng

    e32 = build("float32", None)
    e8 = build("int8", "int8")
    sent.mark_steady()
    ref = e32.generate(prompts, max_new_tokens=24)
    qnt = e8.generate(prompts, max_new_tokens=24)
    agree = sum(1 for a, b in zip(ref, qnt)
                if a.tokens.tolist() == b.tokens.tolist()) / len(ref)
    drift = max(abs(a.logp - b.logp) for a, b in zip(ref, qnt))
    print("PARITY=%.4f" % agree, flush=True)
    print("LOGP_DRIFT=%.4f" % drift, flush=True)
    print("BYTES=%d,%d" % (e32.kv_bytes_per_page(),
                           e8.kv_bytes_per_page()), flush=True)
    e32.stop(); e8.stop()
    m = global_metrics()
    print("RECOMPILES="
          + str(int(m.counter('train.unexpected_recompiles_total'))),
          flush=True)
""")


def _run_quant_parity() -> dict:
    """Run the parity drill subprocess; parse its KEY=value lines."""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.pathsep.join(
                   p for p in [REPO, os.environ.get("PYTHONPATH")] if p))
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run([sys.executable, "-c", QUANT_PARITY], env=env,
                          capture_output=True, text=True, timeout=600)
    if proc.returncode != 0:
        raise RuntimeError("quant parity drill died:\n" + proc.stderr[-2000:])
    vals = {}
    for line in proc.stdout.splitlines():
        if "=" in line:
            k, _, v = line.partition("=")
            vals[k.strip()] = v.strip()
    f32_bytes, int8_bytes = (int(x) for x in vals["BYTES"].split(","))
    return {
        "parity": float(vals["PARITY"]),
        "logp_drift": float(vals["LOGP_DRIFT"]),
        "f32_bytes_per_page": f32_bytes,
        "int8_bytes_per_page": int8_bytes,
        "recompiles": int(vals["RECOMPILES"]),
    }


def run_decode_quant(clients: int, duration_s: float, out=None,
                     smoke: bool = False) -> int:
    """The quantized-serving smoke gate (docs/quantization.md §Serving
    memory hierarchy): greedy token parity int8-vs-f32, >= 1.8x slot
    capacity at an EQUAL KV HBM budget, zero unexpected recompiles on
    every arm, and (non-smoke) quantized tokens/s within 10% of the f32
    arm run in the same invocation."""
    par = _run_quant_parity()
    # equal HBM budget: the f32 arm's 8 slots of pages, re-spent on
    # int8 pages (per-page scales included in int8_bytes_per_page)
    base_slots = 8
    ratio = par["f32_bytes_per_page"] / par["int8_bytes_per_page"]
    quant_slots = max(1, int(base_slots * ratio))
    f32 = run_decode_bench(True, clients, duration_s, slots=base_slots,
                           kv_dtype="float32")
    quant = run_decode_bench(True, clients, duration_s,
                             slots=quant_slots, kv_dtype="int8",
                             weight_quant="int8")
    row = {
        "bench": "decode_quant",
        "geometry": f"decode_s{base_slots}q{quant_slots}_c{clients}",
        "concurrent_clients": clients,
        "kv_dtype": "int8",
        "weight_quant": "int8",
        "f32_kv_bytes_per_page": par["f32_bytes_per_page"],
        "int8_kv_bytes_per_page": par["int8_bytes_per_page"],
        "f32_slots": base_slots,
        "int8_slots_equal_hbm": quant_slots,
        "slots_per_chip_ratio": round(quant_slots / base_slots, 2),
        "token_parity": par["parity"],
        "logp_drift_max": par["logp_drift"],
        "f32_tokens_per_s": f32["tokens_per_s"],
        "quant_tokens_per_s": quant["tokens_per_s"],
        "quant_ttft_ms_p99": quant["ttft_ms_p99"],
        "unexpected_recompiles": (par["recompiles"]
                                  + f32["unexpected_recompiles"]
                                  + quant["unexpected_recompiles"]),
    }
    failures = []
    if par["parity"] < 1.0:
        failures.append(f"greedy token parity {par['parity']:.2f} < 1.0 "
                        "(int8 KV + int8 weights vs f32)")
    if row["slots_per_chip_ratio"] < 1.8:
        failures.append(f"int8 slots only {row['slots_per_chip_ratio']}x "
                        "f32 at equal HBM budget (< 1.8x)")
    if row["unexpected_recompiles"] != 0:
        failures.append(f"{row['unexpected_recompiles']} unexpected XLA "
                        "recompiles across the quant sweep")
    for arm, name in ((f32, "f32"), (quant, "int8")):
        if arm["tokens"] <= 0:
            failures.append(f"{name} arm: no tokens generated")
    if not smoke and f32["tokens_per_s"] > 0:
        rel = quant["tokens_per_s"] / f32["tokens_per_s"]
        if rel < 0.9:
            failures.append(f"quantized tokens/s only {rel:.2f}x the f32 "
                            "arm (< 0.9x): dequant overhead regressed")
    if out and not failures:
        with open(out, "w") as f:
            json.dump(row, f, indent=1)
    print(json.dumps(row))
    if failures:
        print("FAIL: " + "; ".join(failures), file=sys.stderr)
        return 1
    return 0


# ---------------------------------------------------------------------------
# speculative decode bench (--decode --spec): the DECODE_SPEC_r*.json
# evidence source (docs/serving.md §Speculative decoding)
# ---------------------------------------------------------------------------

# Engine-level parity drill in its own interpreter: the SAME tiny LM
# spec-off vs spec-on (weight-shared block-sparse draft, k tokens per
# iteration, single-call verify), greedy AND seeded-sample over an
# identical mixed-geometry batch.  Speculation must be invisible in the
# output: byte-identical tokens and logp on both legs (the acceptance
# rule emits only target selections).  Prints the agreement fraction,
# the accept rate, and the unexpected-recompile counter (both engines
# warm BEFORE mark_steady — the draft/verify programs joining the
# compile set is expected; anything after is not).
SPEC_PARITY = textwrap.dedent("""
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")

    from bigdl_tpu.nn.attention import Transformer
    from bigdl_tpu.obs.attr import recompile_sentinel
    from bigdl_tpu.optim.metrics import global_metrics
    from bigdl_tpu.serving.decode_engine import (DecodeConfig,
                                                 DecodeEngine, LMAdapter,
                                                 SpecConfig)

    sent = recompile_sentinel().install()
    model = Transformer(vocab_size=64, hidden_size=32, num_heads=2,
                        num_layers=2, dropout=0.0, mode="lm")
    params = model.init(jax.random.PRNGKey(0),
                        np.arange(8, dtype=np.int32)[None])["params"]
    rs = np.random.RandomState(0)
    prompts = [rs.randint(2, 64, (int(rs.randint(4, 17)),)).tolist()
               for _ in range(8)]

    def build(spec):
        cfg = DecodeConfig(slots=4, page_size=8, pages_per_slot=16,
                           prompt_chunk=8, max_new_tokens=32, eos_id=1,
                           speculative=spec)
        eng = DecodeEngine(LMAdapter(model, params, cap=cfg.cap), cfg)
        eng.warmup()
        return eng

    off = build(None)
    on = build(SpecConfig(k=%(k)d, sparsity=%(sparsity)r))
    chunk = build(SpecConfig(k=%(k)d, sparsity=%(sparsity)r,
                             verify_impl="chunk"))
    sent.mark_steady()
    agree = chunk_agree = 0
    for kw in ({}, dict(temperature=0.9, top_k=8, top_p=0.9)):
        ref = off.generate(prompts, max_new_tokens=24, **kw)
        spc = on.generate(prompts, max_new_tokens=24, **kw)
        chk = chunk.generate(prompts, max_new_tokens=24, **kw)
        agree += sum(1 for a, b in zip(ref, spc)
                     if a.tokens.tolist() == b.tokens.tolist()
                     and np.float32(a.logp) == np.float32(b.logp))
        # the chunk verify is a different (multi-query) program: token
        # stream still exact, logp pinned to allclose (same math, one
        # batched softmax instead of k+1 single-token ones)
        chunk_agree += sum(1 for a, b in zip(ref, chk)
                           if a.tokens.tolist() == b.tokens.tolist()
                           and np.allclose(a.logp, b.logp,
                                           rtol=2e-5, atol=2e-5))
    st = on.stats
    adjud = st['spec_accepted'] + st['spec_rejected']
    print("PARITY=%%.4f" %% (agree / (2 * len(prompts))), flush=True)
    print("CHUNK_PARITY=%%.4f" %% (chunk_agree / (2 * len(prompts))),
          flush=True)
    print("ACCEPT=%%.4f" %% (st['spec_accepted'] / max(adjud, 1)),
          flush=True)
    off.stop(); on.stop(); chunk.stop()
    m = global_metrics()
    print("RECOMPILES="
          + str(int(m.counter('train.unexpected_recompiles_total'))),
          flush=True)
""")


# The throughput A/B in its own interpreter, at the geometry where
# speculation's physics live: LONG context (768-token cap, 150-250
# token prompts, 480-token decodes).  Per decoded token the spec-off
# engine re-reads the slot's whole KV pool to score ONE position; the
# draft pays that same read k+1 times but the verify scores k+1
# positions in a single pass over it, so the pool traffic per EMITTED
# token drops by the acceptance-weighted chunk length.  Short-context
# geometries hide this (the pool read is too cheap to amortize) — the
# committed artifact says so via the geometry field.  Arms run ABBA
# (off,on,on,off) per wave with a shared warm wave first: on the
# 1-CPU bench host wall-clock drifts +/-30%% run to run, and pairing
# cancels it where back-to-back arms would bake it in.  Both engines
# warm BEFORE mark_steady; every wave after is a zero-recompile gate.
SPEC_AB = textwrap.dedent("""
    import time
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")

    from bigdl_tpu.nn.attention import Transformer
    from bigdl_tpu.obs.attr import recompile_sentinel
    from bigdl_tpu.optim.metrics import global_metrics
    from bigdl_tpu.serving.decode_engine import (DecodeConfig,
                                                 DecodeEngine,
                                                 DecodeRequest,
                                                 LMAdapter, SpecConfig)

    sent = recompile_sentinel().install()
    model = Transformer(vocab_size=64, hidden_size=32, num_heads=2,
                        num_layers=2, dropout=0.0, mode="lm")
    params = model.init(jax.random.PRNGKey(0),
                        np.arange(8, dtype=np.int32)[None])["params"]

    def build(spec):
        cfg = DecodeConfig(slots=%(slots)d, page_size=16,
                           pages_per_slot=%(pps)d, prompt_chunk=64,
                           max_new_tokens=%(horizon)d, eos_id=1,
                           speculative=spec)
        eng = DecodeEngine(LMAdapter(model, params, cap=cfg.cap), cfg)
        eng.warmup()
        return eng

    off = build(None)
    on = build(SpecConfig(k=%(k)d, sparsity=%(sparsity)r,
                          verify_impl=%(verify_impl)r))
    sent.mark_steady()

    def wave(eng, seed):
        rs = np.random.RandomState(seed)
        reqs = [DecodeRequest(
                    tokens=rs.randint(2, 64, (int(rs.randint(
                        %(plo)d, %(phi)d)),)).astype(np.int32),
                    max_new_tokens=%(new)d, seed=seed * 100 + i)
                for i in range(%(conc)d)]
        t0 = time.perf_counter()
        for r in reqs:
            eng.submit(r)
        outs = [r.wait(timeout=600) for r in reqs]
        dt = time.perf_counter() - t0
        toks = sum(len(o.tokens) for o in outs)
        assert toks > 0, "wave produced no tokens"
        return toks / dt / %(conc)d

    wave(off, 0); wave(on, 0)   # shared warm wave, outside the window
    for w in range(1, %(waves)d + 1):
        a1 = wave(off, w); b1 = wave(on, w)
        b2 = wave(on, w + 100); a2 = wave(off, w + 100)
        print("WAVE=%%.4f,%%.4f" %% (a1 + a2, b1 + b2), flush=True)
    st = on.stats
    adjud = st['spec_accepted'] + st['spec_rejected']
    print("ACCEPT=%%.4f" %% (st['spec_accepted'] / max(adjud, 1)),
          flush=True)
    print("DRAFTED=%%d" %% st['spec_drafted'], flush=True)
    off.stop(); on.stop()
    m = global_metrics()
    print("RECOMPILES="
          + str(int(m.counter('train.unexpected_recompiles_total'))),
          flush=True)
""")


def _run_spec_ab(k: int, sparsity: float, verify_impl: str,
                 smoke: bool) -> dict:
    """Run the paired long-context A/B subprocess; parse its lines.
    Smoke collapses the geometry (256-token cap, 48-token decodes, one
    wave) — it exercises the identical wave/pairing machinery and the
    zero-recompile gate, just not the speedup floor."""
    geo = dict(slots=4, conc=4, k=k, sparsity=sparsity,
               verify_impl=verify_impl)
    if smoke:
        geo.update(pps=16, horizon=64, plo=40, phi=80, new=48, waves=1)
    else:
        geo.update(pps=48, horizon=520, plo=150, phi=250, new=480,
                   waves=3)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.pathsep.join(
                   p for p in [REPO, os.environ.get("PYTHONPATH")] if p))
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run([sys.executable, "-c", SPEC_AB % geo], env=env,
                          capture_output=True, text=True, timeout=900)
    if proc.returncode != 0:
        raise RuntimeError("spec A/B died:\n" + proc.stderr[-2000:])
    waves, vals = [], {}
    for line in proc.stdout.splitlines():
        if line.startswith("WAVE="):
            a, _, b = line[5:].partition(",")
            waves.append((float(a), float(b)))
        elif "=" in line:
            key, _, v = line.partition("=")
            vals[key.strip()] = v.strip()
    return {
        "geometry": ("decode_spec_s4_c4_ctx256_smoke" if smoke
                     else "decode_spec_s4_c4_ctx768"),
        "waves": waves,
        "accept_rate": float(vals["ACCEPT"]),
        "drafted": int(vals["DRAFTED"]),
        "recompiles": int(vals["RECOMPILES"]),
    }


def _run_spec_parity(k: int, sparsity: float) -> dict:
    """Run the spec parity drill subprocess; parse its KEY=value lines."""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.pathsep.join(
                   p for p in [REPO, os.environ.get("PYTHONPATH")] if p))
    env.pop("XLA_FLAGS", None)
    code = SPEC_PARITY % {"k": k, "sparsity": sparsity}
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=600)
    if proc.returncode != 0:
        raise RuntimeError("spec parity drill died:\n" + proc.stderr[-2000:])
    vals = {}
    for line in proc.stdout.splitlines():
        if "=" in line:
            key, _, v = line.partition("=")
            vals[key.strip()] = v.strip()
    return {
        "parity": float(vals["PARITY"]),
        "chunk_parity": float(vals["CHUNK_PARITY"]),
        "accept_rate": float(vals["ACCEPT"]),
        "recompiles": int(vals["RECOMPILES"]),
    }


def run_decode_spec(out=None, smoke: bool = False, k: int = 48,
                    sparsity: float = 0.5,
                    verify_impl: str = "chunk") -> int:
    """The speculative-decoding gate (docs/serving.md §Speculative
    decoding).  Two drills, each its own interpreter:

    1. Parity: spec-on vs spec-off over an identical batch, greedy AND
       seeded sample.  Scan verify must be BYTE-identical (tokens and
       logp); the chunk verify must match tokens exactly with logp
       allclose.
    2. Throughput: ABBA-paired waves at the long-context geometry,
       tokens/s/user spec-on vs spec-off, median-of-waves speedup
       gated >= 1.5x (non-smoke).

    Zero unexpected recompiles across both drills — every draft /
    verify / step / prefill program joins warmup()'s closed bucket
    set before mark_steady."""
    par = _run_spec_parity(k, sparsity)
    ab = _run_spec_ab(k, sparsity, verify_impl, smoke)
    ratios = sorted(b / a for a, b in ab["waves"] if a > 0)
    speedup = (round(ratios[len(ratios) // 2], 2) if ratios else 0.0)
    off_rate = sorted(a for a, _ in ab["waves"])[len(ab["waves"]) // 2]
    on_rate = sorted(b for _, b in ab["waves"])[len(ab["waves"]) // 2]
    row = {
        "bench": "decode_spec",
        "geometry": ab["geometry"],
        "concurrent_clients": 4,
        "spec_k": k,
        "spec_sparsity": sparsity,
        "spec_verify_impl": verify_impl,
        "token_parity": par["parity"],
        "chunk_token_parity": par["chunk_parity"],
        "accept_rate": ab["accept_rate"],
        "parity_accept_rate": par["accept_rate"],
        # median per-wave PAIRED rates (each wave sums its two ABBA
        # runs); the speedup is the median of per-wave ratios, not the
        # ratio of medians — pairing is what cancels host drift
        "spec_tokens_per_s_user": round(on_rate / 2, 2),
        "base_tokens_per_s_user": round(off_rate / 2, 2),
        "wave_speedups": [round(r, 3) for r in ratios],
        "speedup_vs_off": speedup,
        "spec_drafted": ab["drafted"],
        "unexpected_recompiles": (par["recompiles"]
                                  + ab["recompiles"]),
    }
    failures = []
    if par["parity"] < 1.0:
        failures.append(f"token/logp parity {par['parity']:.2f} < 1.0 "
                        "(spec-on vs spec-off must be byte-identical)")
    if par["chunk_parity"] < 1.0:
        failures.append(f"chunk-verify parity {par['chunk_parity']:.2f}"
                        " < 1.0 (tokens exact, logp allclose)")
    if row["unexpected_recompiles"] != 0:
        failures.append(f"{row['unexpected_recompiles']} unexpected XLA "
                        "recompiles across the spec sweep")
    if ab["drafted"] <= 0:
        failures.append("spec-on arm never drafted — speculation "
                        "silently disabled")
    if not smoke and speedup < 1.5:
        failures.append(f"speculative tokens/s/user only {speedup}x the "
                        "spec-off arm (< 1.5x median of paired waves)")
    if out and not failures:
        with open(out, "w") as f:
            json.dump(row, f, indent=1)
    print(json.dumps(row))
    if failures:
        print("FAIL: " + "; ".join(failures), file=sys.stderr)
        return 1
    return 0


# ---------------------------------------------------------------------------
# disaggregated decode-fleet bench (--fleet): the DECODE_POOL_r*.json
# evidence source (docs/serving.md §Decode fleet)
# ---------------------------------------------------------------------------


def _fleet_loader():
    """Worker-side factory (``bench_serving:_fleet_loader`` in the worker
    interpreter): the SAME tiny LM as the single-host decode bench, with
    a SMALLER slot pool (5 vs 8) — on the CPU bench host the decode
    worker is bound by token DELIVERY (callback -> handler write ->
    relay -> client read all timeshare the cores), not step compute, so
    each extra concurrently-streaming slot stretches the inter-token
    tail by a whole delivery burst; 5 slots keeps the burst short while
    the disaggregated prefill worker absorbs the long-prompt admission
    work that would otherwise stall those bursts.  Everything else
    (model, pages, chunking, request mix) matches DECODE_r*.json so the
    TTFT comparison is honest — plus the fleet pieces (prefix cache;
    the handoff path needs no config).  Installs the recompile sentinel
    so the pool's federated /metrics carries every worker's
    ``train_unexpected_recompiles_total``."""
    import jax
    import numpy as np

    from bigdl_tpu.nn.attention import Transformer
    from bigdl_tpu.obs.attr import recompile_sentinel
    from bigdl_tpu.serving import DecodeConfig, InferenceModel

    jax.config.update("jax_platforms", "cpu")
    sent = recompile_sentinel().install()
    model = Transformer(vocab_size=64, hidden_size=32, num_heads=2,
                        num_layers=2, dropout=0.0, mode="lm")
    variables = model.init(jax.random.PRNGKey(0),
                           np.arange(8, dtype=np.int32)[None])
    slots = int(os.environ.get("BIGDL_TPU_FLEET_SLOTS", "5"))
    im = InferenceModel(model, variables, decode=DecodeConfig(
        slots=slots, page_size=8, pages_per_slot=16, prompt_chunk=8,
        max_new_tokens=120, eos_id=1, prefix_cache_pages=16))
    eng = im.decode_engine
    eng.warmup()
    # chaos drill only: throttle the decode loop so the tiny CPU model
    # holds streams in flight long enough for the mid-run SIGKILL to
    # land on live slots (both phases get the same throttle — the
    # baseline/chaos throughput comparison stays honest)
    sleep_s = float(os.environ.get("BIGDL_TPU_CHAOS_DECODE_SLEEP",
                                   "0") or 0)
    if sleep_s > 0:
        import time as _time
        orig_step = eng._decode_step

        def _throttled_step():
            _time.sleep(sleep_s)
            return orig_step()

        eng._decode_step = _throttled_step
    sent.mark_steady()
    return im


FLEET_SERVER = textwrap.dedent("""
    import sys, threading, time
    from bigdl_tpu.serving.pool import ServingPool

    pool = ServingPool("bench_serving:_fleet_loader",
                       workers=%(workers)d, batch_size=8,
                       roles=%(roles)r, worker_env=%(env)r,
                       fleet_split_min_tokens=%(split_min)d,
                       supervise_interval_s=0.5,
                       predict_timeout=%(predict_timeout)f)
    pool.start()

    def _chaos_kill(after):
        # chaos drill (--fleet --chaos): once enough client streams are
        # in flight, SIGKILL one decode-capable worker mid-stream — the
        # proxy must fail its streams over with token parity.  Target a
        # worker that is actually HOLDING live generates (the router may
        # have packed the whole first wave on one worker): a kill that
        # lands on an idle peer proves nothing
        while pool.stats["stream_relays"] < after:
            time.sleep(0.02)
        live = [w for w in reversed(pool.worker_list())
                if w.role != "prefill" and w.alive()]
        victim = None
        deadline = time.time() + 30.0
        while victim is None and time.time() < deadline:
            for w in live:
                h = pool._worker_health(w)
                if (h or {}).get("decode", {}).get(
                        "generate_inflight", 0) >= 1:
                    victim = w
                    break
            else:
                time.sleep(0.02)
        if victim is None and live:
            victim = live[0]
        if victim is not None:
            victim.proc.kill()
            print("KILLED=" + victim.name, flush=True)

    if %(kill_after)d:
        threading.Thread(target=_chaos_kill, args=(%(kill_after)d,),
                         daemon=True).start()
    print(f"URL={pool.url}", flush=True)
    sys.stdin.readline()
    pool.stop()
""")


class _FleetServer:
    """The pool subprocess: proxy + role-assigned workers.  Scraping
    (federated /metrics, /health) happens from the PARENT while the pool
    is still up — ``scrape()`` before ``finish()``.  ``kill_after`` > 0
    arms the chaos thread: one decode-capable worker is SIGKILLed once
    that many client streams have started relaying."""

    def __init__(self, workers: int, roles, split_min: int = 0,
                 kill_after: int = 0, predict_timeout: float = 30.0,
                 decode_sleep: float = 0.0):
        env = {"PYTHONPATH": os.pathsep.join(
                   p for p in [REPO, os.environ.get("PYTHONPATH")] if p),
               "JAX_PLATFORMS": "cpu", "BIGDL_TPU_POOL_CPU": "1"}
        if os.environ.get("BIGDL_TPU_FLEET_SLOTS"):
            env["BIGDL_TPU_FLEET_SLOTS"] = \
                os.environ["BIGDL_TPU_FLEET_SLOTS"]
        if decode_sleep > 0:
            env["BIGDL_TPU_CHAOS_DECODE_SLEEP"] = str(decode_sleep)
        code = FLEET_SERVER % {"workers": workers, "roles": list(roles),
                               "env": env, "split_min": split_min,
                               "kill_after": kill_after,
                               "predict_timeout": predict_timeout}
        penv = dict(os.environ, JAX_PLATFORMS="cpu",
                    PYTHONPATH=env["PYTHONPATH"])
        penv.pop("XLA_FLAGS", None)
        self.proc = subprocess.Popen([sys.executable, "-c", code],
                                     env=penv, stdin=subprocess.PIPE,
                                     stdout=subprocess.PIPE, text=True)
        self.url = None
        deadline = time.time() + 240 + 60 * workers
        while time.time() < deadline and self.url is None:
            line = self.proc.stdout.readline().strip()
            if line.startswith("URL="):
                self.url = line[4:]
            elif not line and self.proc.poll() is not None:
                raise RuntimeError("fleet pool died on startup")
        if self.url is None:
            self.proc.kill()
            raise RuntimeError("fleet pool never printed its URL")
        host, _, port = self.url.split("//", 1)[1].partition(":")
        self.host, self.port = host, int(port)

    def scrape(self) -> dict:
        """Fleet-level evidence while the workers are alive: the summed
        recompile counter from the federated exposition, KV handoff +
        prefix-cache totals from /health, and the proxy's routing
        counters."""
        from urllib import request as _rq

        with _rq.urlopen(self.url + "/metrics", timeout=30) as r:
            text = r.read().decode()
        recompiles = sum(
            int(float(line.rsplit(None, 1)[1]))
            for line in text.splitlines()
            if line.startswith("train_unexpected_recompiles_total"))
        with _rq.urlopen(self.url + "/health", timeout=30) as r:
            health = json.loads(r.read())
        kv_exports = kv_imports = hits = misses = 0
        for w in health.get("workers", []):
            d = w.get("decode") or {}
            kv_exports += int(d.get("kv_exports", 0))
            kv_imports += int(d.get("kv_imports", 0))
            pc = d.get("prefix_cache") or {}
            hits += int(pc.get("hits", 0))
            misses += int(pc.get("misses", 0))
        return {"unexpected_recompiles": recompiles,
                "kv_exports": kv_exports, "kv_imports": kv_imports,
                "prefix_cache_hits": hits, "prefix_cache_misses": misses,
                "completed_requests": int(health.get("requests", 0)),
                **{k: health["pool"][k] for k in
                   ("fleet_routed", "fleet_split", "stream_relays")}}

    def finish(self) -> None:
        try:
            self.proc.stdin.close()
            self.proc.wait(timeout=120)
        except Exception:  # noqa: BLE001 — a hung pool must not hang CI
            self.proc.kill()


def run_fleet_bench(workers: int, roles, clients: int,
                    duration_s: float, split_min: int = 0) -> dict:
    server = _FleetServer(workers, roles, split_min=split_min)
    try:
        # warm phase outside the window: relay paths, handoff channel,
        # worker handler threads, client conns
        _decode_load(server, clients, min(0.6, duration_s))
        ttfts, gaps, counts, wall, errors = _decode_load(
            server, clients, duration_s)
        if errors:
            raise RuntimeError(f"{len(errors)} client errors: {errors[0]}")
        fleet = server.scrape()
    finally:
        server.finish()
    tokens = int(sum(counts))
    return {
        "engine": "decode_pool",
        "geometry": f"decode_pool_w{workers}_c{clients}",
        "workers": workers,
        "roles": ",".join(roles),
        "concurrent_clients": clients,
        "duration_s": round(wall, 2),
        "requests": len(ttfts),
        "tokens": tokens,
        "tokens_per_s": round(tokens / wall, 1),
        "tokens_per_s_user": round(tokens / wall / clients, 2),
        "ttft_ms_p50": round(_pct(ttfts, 0.50) * 1e3, 2),
        "ttft_ms_p99": round(_pct(ttfts, 0.99) * 1e3, 2),
        "inter_token_p99_ms": round(_pct(gaps, 0.99) * 1e3, 2),
        "streaming_clients": True,
        **fleet,
    }


def _single_host_ttft_baseline() -> float:
    """The committed single-host decode TTFT p99 the fleet must halve
    (ISSUE gate: disaggregation + capacity, not a lucky run)."""
    try:
        with open(os.path.join(REPO, "DECODE_r01.json")) as f:
            return float(json.load(f)["ttft_ms_p99"])
    except Exception:  # noqa: BLE001 — artifact not committed yet
        return 3028.92


def run_fleet(clients: int, duration_s: float, out=None,
              smoke: bool = False) -> int:
    """One fleet row: a dedicated prefill worker feeding decode workers
    over the serialized KV-handoff channel, streaming mixed-geometry
    clients through the pool proxy's relay.  Smoke keeps the split
    live-or-fail gates; the full run adds the TTFT/inter-token gates
    against the committed single-host baseline."""
    workers, roles = 2, ("prefill", "decode")
    # Split threshold: the handoff has a fixed cost (harvest + serialize
    # + HTTP hop + import) that only beats local recompute past a prompt
    # length, so the full run splits only the long tail of the mixed
    # geometry.  Smoke forces split_min=0 — its 1.5 s window must
    # exercise the handoff channel deterministically, not probabilistically.
    split_min = 0 if smoke else 16
    if smoke:
        clients, duration_s = 6, 1.5
    row = run_fleet_bench(workers, roles, clients, duration_s,
                          split_min=split_min)
    failures = []
    if row["tokens"] <= 0:
        failures.append("no tokens generated")
    if row["unexpected_recompiles"] != 0:
        failures.append(f"{row['unexpected_recompiles']} unexpected XLA "
                        "recompiles across the fleet")
    if row["fleet_split"] < 1 or row["kv_imports"] < 1:
        failures.append("the prefill/decode split never happened "
                        f"(fleet_split={row['fleet_split']}, "
                        f"kv_imports={row['kv_imports']})")
    if row["stream_relays"] < 1:
        failures.append("no streams relayed through the proxy")
    if not smoke:
        ttft_gate = _single_host_ttft_baseline() / 2.0
        if row["ttft_ms_p99"] > ttft_gate:
            failures.append(f"TTFT p99 {row['ttft_ms_p99']}ms > "
                            f"{ttft_gate:.0f}ms (2x single-host gate)")
        if row["inter_token_p99_ms"] > 10.0:
            failures.append(f"inter-token p99 "
                            f"{row['inter_token_p99_ms']}ms > 10ms")
    if out:
        with open(out, "w") as f:
            json.dump(row, f, indent=1)
    print(json.dumps(row))
    if failures:
        print("FAIL: " + "; ".join(failures), file=sys.stderr)
        return 1
    return 0


# ---------------------------------------------------------------------------
# decode-fleet chaos drill (--fleet --chaos): the DECODE_CHAOS_r*.json
# evidence source (docs/serving.md §Fleet fault tolerance)
# ---------------------------------------------------------------------------


def _chaos_request_set(clients: int, per_client: int, seed: int = 7):
    """A FIXED, seeded request set — the same list runs in the no-fault
    baseline phase and the chaos phase, so token parity is a strict
    equality check, not a statistic.  Half the requests are greedy
    (temperature 0), half seeded sampling — both must survive a failover
    byte-identically (the engine keys sampling on absolute position, not
    on who computed the prefix).  Each client's FIRST request carries a
    long output so the mid-run kill lands while most of the first wave
    is still streaming."""
    rs = np.random.RandomState(seed)
    reqs = []
    for ci in range(clients):
        for j in range(per_client):
            plen = int(rs.randint(4, 17))
            max_new = int(rs.randint(48, 81) if j == 0
                          else rs.randint(8, 25))
            seeded = bool(rs.rand() < 0.5)
            reqs.append({
                "client": ci, "rid": f"chaos-{ci}-{j}",
                "tokens": rs.randint(2, 64, (plen,)).tolist(),
                "max_new_tokens": max_new,
                "temperature": 0.8 if seeded else 0.0,
                "top_k": 0, "top_p": 1.0,
                "seed": int(rs.randint(0, 2 ** 31 - 1))})
    return reqs


def _chaos_clients(host: str, port: int, reqs, clients: int):
    """The chaos drill's measuring clients: one thread per client, each
    posting its fixed request list sequentially over a keep-alive
    connection.  Unlike the perf loops, EVERY token line is parsed —
    parity is the gate — and each stream's worst inter-token gap is kept
    as the client-visible recovery latency.  Returns
    ``({rid: tokens}, {rid: max_gap_s}, [(rid, error), ...])``."""
    import http.client as _hc

    by_client = {}
    for r in reqs:
        by_client.setdefault(r["client"], []).append(r)
    results, maxgaps, failed = {}, {}, []
    lock = threading.Lock()

    def one(conn, body):
        for attempt in (0, 1):
            if conn is None:
                conn = _hc.HTTPConnection(host, port, timeout=240.0)
            try:
                conn.request("POST", "/generate", body=body,
                             headers={"Content-Type": "application/json"})
                resp = conn.getresponse()
            except Exception:
                conn.close()
                conn = None
                if attempt:
                    raise
                continue  # stale keep-alive socket: one fresh retry
            if resp.status != 200:
                raise RuntimeError(f"HTTP {resp.status}: "
                                   f"{resp.read()[:200]!r}")
            toks, times, final = [], [], None
            while True:
                line = resp.readline()
                if not line:
                    break
                line = line.strip()
                if not line:
                    continue
                ev = json.loads(line)
                if "error" in ev:
                    raise RuntimeError(f"stream error: {ev['error']}")
                if "token" in ev:
                    toks.append(int(ev["token"]))
                    times.append(time.time())
                if ev.get("done"):
                    final = [int(t) for t in ev.get("tokens") or []]
                    break
            resp.read()  # drain the terminal chunk: conn stays reusable
            if final is None:
                # a silent truncation — exactly what failover exists to
                # prevent; the orphan path would have sent an error line
                raise RuntimeError("stream ended without a final verdict")
            if toks and toks != final:
                raise RuntimeError("streamed tokens diverge from the "
                                   f"final verdict: {toks} vs {final}")
            return conn, final, times
        raise RuntimeError("unreachable")

    def run(ci):
        conn = None
        try:
            for r in by_client.get(ci, []):
                body = json.dumps({
                    "tokens": r["tokens"],
                    "max_new_tokens": r["max_new_tokens"],
                    "temperature": r["temperature"],
                    "top_k": r["top_k"], "top_p": r["top_p"],
                    "seed": r["seed"], "stream": True,
                    "request_id": r["rid"]}).encode()
                try:
                    conn, final, times = one(conn, body)
                except Exception as e:  # noqa: BLE001 — the gate counts it
                    with lock:
                        failed.append((r["rid"], str(e)))
                    if conn is not None:
                        conn.close()
                    conn = None
                    continue
                gap = max((b - a for a, b in zip(times, times[1:])),
                          default=0.0)
                with lock:
                    results[r["rid"]] = final
                    maxgaps[r["rid"]] = gap
        finally:
            if conn is not None:
                conn.close()

    threads = [threading.Thread(target=run, args=(ci,))
               for ci in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=600)
    return results, maxgaps, failed


def _chaos_phase(reqs, clients: int, chaos: bool):
    """One phase of the drill on a FRESH pool (two "both"-role workers,
    so a killed worker's streams have a live peer to fail over to
    immediately — the supervisor's respawn is the backstop, not the
    recovery path).  Returns the client results plus the proxy's stats
    and restart count, scraped while the pool is still up."""
    kill_after = max(2, clients // 3) if chaos else 0
    server = _FleetServer(2, ("both", "both"), split_min=0,
                          kill_after=kill_after, predict_timeout=60.0,
                          decode_sleep=0.008)
    t0 = time.time()
    try:
        results, maxgaps, failed = _chaos_clients(
            server.host, server.port, reqs, clients)
        wall = time.time() - t0
        from urllib import request as _rq

        with _rq.urlopen(server.url + "/health", timeout=30) as r:
            health = json.loads(r.read())
    finally:
        server.finish()
    return results, maxgaps, failed, {
        "stats": health.get("pool", {}),
        "restarts": int(health.get("restarts", 0))}, wall


def run_fleet_chaos(clients: int, out=None, smoke: bool = False) -> int:
    """The DECODE_CHAOS_r*.json drill: the same fixed request set runs
    against a clean pool (baseline) and against a pool where one decode
    worker is SIGKILLed mid-run.  Gates: ZERO failed requests under
    chaos, byte-identical token sequences for every request (greedy and
    seeded), at least one observed failover, no orphaned streams, and a
    bounded client-visible recovery tail."""
    per_client = 2
    if smoke:
        clients = 6
    reqs = _chaos_request_set(clients, per_client)
    base, _, base_failed, _, _ = _chaos_phase(reqs, clients, chaos=False)
    got, maxgaps, failed, fleet, wall = _chaos_phase(reqs, clients,
                                                     chaos=True)
    stats = fleet["stats"]
    mismatched = [r["rid"] for r in reqs
                  if got.get(r["rid"]) != base.get(r["rid"])]
    recovery_ms_p99 = round(_pct(list(maxgaps.values()), 0.99) * 1e3, 2)
    tokens = sum(len(v) for v in got.values())
    row = {
        "bench": "decode_chaos",
        "engine": "decode_pool",
        "geometry": f"decode_chaos_w2_c{clients}",
        "workers": 2,
        "concurrent_clients": clients,
        "requests": len(reqs),
        "duration_s": round(wall, 2),
        "tokens": tokens,
        "chaos_tokens_per_s": round(tokens / wall, 1) if wall else 0.0,
        "failed_requests": len(failed),
        "baseline_failed_requests": len(base_failed),
        "parity_ok": not mismatched,
        "failovers": int(stats.get("fleet_failovers", 0)),
        "migrations": int(stats.get("fleet_migrations", 0)),
        "resumed_tokens": int(stats.get("fleet_resumed_tokens", 0)),
        "orphaned_requests": int(stats.get("fleet_orphans", 0)),
        "worker_restarts": fleet["restarts"],
        "recovery_ms_p99": recovery_ms_p99,
        "streaming_clients": True,
    }
    failures = []
    if base_failed:
        failures.append(f"{len(base_failed)} baseline failures "
                        f"(first: {base_failed[0]})")
    if failed:
        failures.append(f"{len(failed)} failed requests under chaos "
                        f"(first: {failed[0]})")
    if mismatched:
        failures.append(f"token parity broken across the failover for "
                        f"{mismatched[:4]}")
    if row["failovers"] < 1:
        failures.append("no failover observed — the kill missed every "
                        "in-flight stream")
    if row["orphaned_requests"]:
        failures.append(f"{row['orphaned_requests']} streams orphaned")
    bound_ms = 30000.0 if smoke else 20000.0
    if recovery_ms_p99 > bound_ms:
        failures.append(f"recovery p99 {recovery_ms_p99}ms > "
                        f"{bound_ms:.0f}ms")
    if out and not failures:
        with open(out, "w") as f:
            json.dump(row, f, indent=1)
    print(json.dumps(row))
    if failures:
        print("FAIL: " + "; ".join(failures), file=sys.stderr)
        return 1
    return 0


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if argv and argv[0] == "--decode-worker":
        return _decode_worker_main(argv[1:])
    ap = argparse.ArgumentParser(
        description="sustained-load serving bench (docs/serving.md)")
    ap.add_argument("--clients", type=int, default=32)
    ap.add_argument("--duration", type=float, default=4.0)
    ap.add_argument("--fixed", action="store_true",
                    help="run the legacy fixed-window engine (A/B)")
    ap.add_argument("--smoke", action="store_true",
                    help="CI gate: correctness + batching + zero "
                         "unexpected recompiles on both engines")
    ap.add_argument("--decode", action="store_true",
                    help="token-level decode bench: continuous vs "
                         "whole-batch-restart, streaming clients")
    ap.add_argument("--quant", action="store_true",
                    help="with --decode: int8 KV pages + int8 serving "
                         "weights vs f32 at equal HBM budget — token "
                         "parity, >= 1.8x slots, zero recompiles")
    ap.add_argument("--spec", action="store_true",
                    help="with --decode: speculative decoding with the "
                         "weight-shared block-sparse draft, spec-on vs "
                         "spec-off A/B — byte parity, >= 1.5x tokens/s"
                         "/user, zero recompiles")
    ap.add_argument("--fleet", action="store_true",
                    help="disaggregated decode-fleet bench: prefill/"
                         "decode split over a worker pool, KV-aware "
                         "routing, streaming relay")
    ap.add_argument("--chaos", action="store_true",
                    help="with --fleet: kill a decode worker mid-run and "
                         "gate zero failed requests + token parity + "
                         "bounded recovery")
    ap.add_argument("--out", default=None,
                    help="also write the artifact JSON here")
    args = ap.parse_args(argv)
    if args.fleet and args.chaos:
        out = args.out
        if out is None and os.environ.get("BIGDL_TPU_WRITE_ARTIFACTS"):
            out = os.path.join(REPO, "DECODE_CHAOS_r01.json")
        clients = 24 if args.clients == 32 else args.clients
        return run_fleet_chaos(clients=clients, out=out,
                               smoke=args.smoke)
    if args.fleet:
        if args.smoke:
            return run_fleet(clients=6, duration_s=1.5, smoke=True)
        out = args.out
        if out is None and os.environ.get("BIGDL_TPU_WRITE_ARTIFACTS"):
            out = os.path.join(REPO, "DECODE_POOL_r01.json")
        # the ISSUE geometry: 24 mixed-geometry streaming clients
        clients = 24 if args.clients == 32 else args.clients
        return run_fleet(clients=clients, duration_s=args.duration,
                         out=out)
    if args.decode and args.spec:
        out = args.out
        if out is None and os.environ.get("BIGDL_TPU_WRITE_ARTIFACTS"):
            out = os.path.join(REPO, "DECODE_SPEC_r01.json")
        return run_decode_spec(out=out, smoke=args.smoke)
    if args.decode and args.quant:
        out = args.out
        if out is None and os.environ.get("BIGDL_TPU_WRITE_ARTIFACTS"):
            out = os.path.join(REPO, "DECODE_QUANT_r01.json")
        if args.smoke:
            return run_decode_quant(clients=4, duration_s=1.5, out=out,
                                    smoke=True)
        clients = 24 if args.clients == 32 else args.clients
        return run_decode_quant(clients=clients,
                                duration_s=args.duration, out=out)
    if args.decode:
        clients = args.clients
        if args.smoke:
            return run_decode(clients=4, duration_s=1.5, smoke=True)
        out = args.out
        if out is None and os.environ.get("BIGDL_TPU_WRITE_ARTIFACTS"):
            out = os.path.join(REPO, "DECODE_r01.json")
        return run_decode(clients=clients, duration_s=args.duration,
                          out=out)
    if args.smoke:
        return _smoke()
    row = run_bench(not args.fixed, args.clients, args.duration)
    out = args.out
    if out is None and os.environ.get("BIGDL_TPU_WRITE_ARTIFACTS"):
        out = os.path.join(REPO, "SERVING_r08.json")
    if out:
        with open(out, "w") as f:
            json.dump(row, f, indent=1)
    print(json.dumps(row))
    if row["unexpected_recompiles"] != 0:
        print("FAIL: unexpected XLA recompiles during the mixed-size "
              "sweep", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
