"""Secondary headline benchmark: decoder-only transformer LM training
throughput (tokens/sec/chip) with MFU accounting.

BASELINE.json config 3 is the reference's Seq2Seq/Transformer-on-WMT path;
this measures the same model family on the flagship training engine
(`ShardedParameterStep` ZeRO-1) with the causal flash-attention Pallas
kernel in the layer stack.  Transformers keep the MXU far busier than
ResNet's small convs, so this is the framework's best-MFU evidence.

Model: GPT-2-small-class decoder-only LM — 12 layers, d=768, 12 heads,
ffn 3072, vocab 32k, seq 1024, weight-tied output projection
(`nn/attention.py` Transformer(mode="lm")).

Prints ONE JSON line; run by `chipup.py` on chip-up, snapshot goes to
`BENCH_LM_r05.json`.  On CPU it runs a tiny smoke so the harness is
testable without the chip (BENCH_LM_TINY=1 forces it).
"""

import json
import os
import sys
import time

import numpy as np

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".jax_cache"))

import jax

# this image's axon plugin ignores the JAX_PLATFORMS *env var*; honor
# it here so CPU smokes don't hang on a down TPU tunnel (conftest
# does the same for tests)
if os.environ.get("JAX_PLATFORMS") == "cpu":
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp

from bench import _peak_flops


def _analytic_flops_per_token(n_layers, d, seq, vocab):
    """Training FLOPs/token: 3x forward; forward = 2 FLOPs per matmul
    param-use (QKVO 4d^2 + FFN 8d^2 per layer, + vocab projection) plus
    the attention score/value matmuls.  CAUSAL accounting: a token attends
    to seq/2 keys on average, so scores+AV cost 2*(seq/2)*d*2 = 2*seq*d —
    the conservative (undercounting) convention, so MFU is a floor."""
    per_layer = 2 * (12 * d * d) + 2 * seq * d
    return 3 * (n_layers * per_layer + 2 * d * vocab)


def _sparse_ab(b, tiny, n_chips, mesh, crit, rng, V, S, L, D, H, fpt,
               peak):
    """``--sparse``: dense-FFN control vs block-sparse FFN under the
    BLaST schedule, same data/seed/steps.  Prune events rebuild the step
    engine (the mask is static per compiled program) under
    ``expected_compile`` so the recompile sentinel stays quiet; the Adam
    state resets at each event (documented bench simplification — the
    schedule has a handful of events, not one per step)."""
    import jax

    from bigdl_tpu.nn.attention import Transformer
    from bigdl_tpu.obs.attr import expected_compile
    from bigdl_tpu.ops.block_sparse import (BlockPruningSchedule,
                                            prune_model_to_sparsity)
    from bigdl_tpu.optim.optim_method import Adam
    from bigdl_tpu.optim.train_step import ShardedParameterStep

    target = float(os.environ.get("BENCH_LM_SPARSITY", "0.5"))
    block = (16, 16) if tiny else (64, 64)
    warmup, ramp, tail = (2, 4, 3) if tiny else (10, 20, 10)
    n_events = 2 if tiny else 4
    total = warmup + ramp + tail
    sched = BlockPruningSchedule(target, warmup_steps=warmup,
                                 ramp_steps=ramp, n_events=n_events)

    B = b * n_chips
    ids = jax.block_until_ready(jax.jit(
        lambda k: jax.random.randint(k, (B, S), 0, V))(rng))
    tgt = jax.block_until_ready(jax.jit(
        lambda k: jax.random.randint(k, (B, S), 0, V))(
            jax.random.fold_in(rng, 1)))

    def run(mdl, schedule):
        variables = mdl.init(rng, jnp.asarray(ids[:1]))
        prune_at = set(schedule.prune_steps()) if schedule else set()

        def build(vars_):
            step = ShardedParameterStep(mdl, crit,
                                        Adam(learning_rate=1e-4), mesh,
                                        vars_)
            return step, step.shard_batch(ids), step.shard_batch(tgt)

        step, x_dev, y_dev = build(variables)
        trajectory = []  # (sparsity, loss) at each level's last step
        cur_sp = 0.0
        t0 = None
        loss = None
        for i in range(total):
            if i in prune_at:
                trajectory.append((cur_sp, float(np.asarray(loss))))
                cur_sp = schedule.sparsity_at(i)
                v = step.get_variables()
                prune_model_to_sparsity(
                    mdl, v, cur_sp,
                    sample_inputs=(jnp.asarray(ids[:1]),))
                with expected_compile():
                    step, x_dev, y_dev = build(v)
            loss = step.train_step_device(i, rng, x_dev, y_dev)
            if i == total - tail:  # steady-sparsity timing window
                float(np.asarray(loss))  # sync before the clock starts
                t0 = time.perf_counter()
        final = float(np.asarray(loss))
        dt = (time.perf_counter() - t0) / (tail - 1) if tail > 1 else 0.0
        trajectory.append((cur_sp, final))
        assert np.isfinite(final), final
        tps = B * S / dt / n_chips if dt > 0 else None
        return tps, final, trajectory

    dense_model = Transformer(vocab_size=V, hidden_size=D, num_heads=H,
                              ffn_size=4 * D, num_layers=L, dropout=0.0,
                              mode="lm")
    sparse_model = Transformer(vocab_size=V, hidden_size=D, num_heads=H,
                               ffn_size=4 * D, num_layers=L, dropout=0.0,
                               mode="lm", ffn_sparsity=target,
                               sparse_block=block)
    tps_d, loss_d, _ = run(dense_model, None)
    tps_s, loss_s, traj = run(sparse_model, sched)
    rec = {
        "ffn_sparsity": target,
        "sparse_block": list(block),
        "schedule": {"warmup_steps": warmup, "ramp_steps": ramp,
                     "n_events": n_events, "steps": total},
        "tokens_per_sec_chip_dense": round(tps_d, 1) if tps_d else None,
        "tokens_per_sec_chip_sparse": round(tps_s, 1) if tps_s else None,
        # same tokens, same dense-equivalent FLOPs/token: the
        # dense-equivalent MFU ratio IS the throughput ratio
        "mfu_vs_dense": round(tps_s / tps_d, 3) if tps_s and tps_d
        else None,
        "loss_dense": round(loss_d, 5),
        "loss_sparse": round(loss_s, 5),
        "loss_vs_sparsity": [{"sparsity": round(sp, 4),
                              "loss": round(l, 5)}
                             for sp, l in traj],
    }
    if peak and tps_d and tps_s:
        rec["mfu_dense"] = round(tps_d * fpt / peak, 4)
        rec["mfu_sparse_dense_equiv"] = round(tps_s * fpt / peak, 4)
    return rec


def main():
    from bigdl_tpu.nn.attention import Transformer
    from bigdl_tpu.nn.criterion import CrossEntropyCriterion
    from bigdl_tpu.optim.optim_method import Adam
    from bigdl_tpu.optim.train_step import ShardedParameterStep
    from bigdl_tpu.runtime.mesh import MeshSpec, build_mesh

    devices = jax.devices()
    on_tpu = devices[0].platform == "tpu"
    tiny = os.environ.get("BENCH_LM_TINY") == "1" or not on_tpu
    n_chips = len(devices)
    mesh = build_mesh(MeshSpec(), devices=devices)

    if tiny:
        L, D, H, V, S, batches, steps = 2, 64, 4, 512, 128, (2,), 2
    else:
        L, D, H, V, S, batches, steps = 12, 768, 12, 32768, 1024, \
            (4, 8, 16), 10

    model = Transformer(vocab_size=V, hidden_size=D, num_heads=H,
                        ffn_size=4 * D, num_layers=L, dropout=0.0,
                        mode="lm")
    crit = CrossEntropyCriterion()
    rng = jax.random.PRNGKey(0)
    n_params = None

    # per-batch lowering handles for cost analysis: the jitted step plus
    # ShapeDtypeStructs of its args — keeps NO device buffers alive, and
    # every sweep point stays analyzable even when the best batch is not
    # the last one measured
    last_build = {}

    def measure(batch_per_chip):
        nonlocal n_params
        B = batch_per_chip * n_chips
        ids = jax.block_until_ready(jax.jit(
            lambda k: jax.random.randint(k, (B, S), 0, V))(rng))
        tgt = jax.block_until_ready(jax.jit(
            lambda k: jax.random.randint(k, (B, S), 0, V))(
                jax.random.fold_in(rng, 1)))
        variables = model.init(rng, jnp.asarray(ids[:1]))
        if n_params is None:
            n_params = int(sum(np.prod(l.shape) for l in
                               jax.tree_util.tree_leaves(
                                   variables["params"])))
        step = ShardedParameterStep(model, crit, Adam(learning_rate=1e-4),
                                    mesh, variables)
        x_dev = step.shard_batch(ids)
        y_dev = step.shard_batch(tgt)

        def sds(t):
            return jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(jnp.shape(a),
                                               jnp.asarray(a).dtype), t)

        ema_in = step.ema_flat if step.ema_flat is not None \
            else step._ema_dummy
        last_build[batch_per_chip] = (step._train, (
            sds(step.flat_params), sds(ema_in), sds(step.opt_state),
            sds(step.model_state), sds(jnp.asarray(0, jnp.int32)),
            sds(rng), sds(x_dev), sds(y_dev),
            sds(jnp.asarray(1.0, jnp.float32))))
        loss = step.train_step_device(0, rng, x_dev, y_dev)
        float(np.asarray(loss))  # block on the warm-up VALUE
        t0 = time.perf_counter()
        for i in range(steps):
            loss = step.train_step_device(i + 1, rng, x_dev, y_dev)
        final = float(np.asarray(loss))
        dt = (time.perf_counter() - t0) / steps
        assert np.isfinite(final), final
        return B * S / dt / n_chips, dt

    sweep = {}
    best = (0.0, None, None)
    for b in batches:
        try:
            tps, st = measure(b)
        except Exception as e:
            sweep[str(b)] = f"failed: {type(e).__name__}"
            continue
        sweep[str(b)] = round(tps, 1)
        if tps > best[0]:
            best = (tps, b, st)

    if best[1] is None:
        print(json.dumps({"metric": "transformer_lm_train_throughput",
                          "value": None, "unit": "tokens/sec/chip",
                          "error": "all batch sizes failed",
                          "sweep": sweep}))
        return 1

    tps, b, st = best
    fpt = _analytic_flops_per_token(L, D, S, V)
    flops_source = "analytic_3x_fwd_causal"
    # prefer XLA's own cost analysis of the compiled step (exact,
    # includes the attention/vocab matmuls as lowered)
    try:
        train_fn, abstract_args = last_build[b]
        cost = train_fn.lower(*abstract_args).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        f = float(cost.get("flops", -1))
        if f > 0:
            # cost analysis sees the per-device SPMD module: divide by
            # PER-DEVICE tokens (b is already batch-per-chip)
            fpt = f / (b * S)
            flops_source = "xla_cost_analysis"
    except Exception:
        pass
    achieved = tps * fpt
    peak = _peak_flops(devices[0].device_kind) if on_tpu else None
    mfu = round(achieved / peak, 4) if peak else None
    out = {
        "metric": "transformer_lm_train_throughput",
        "value": round(tps, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": None,  # reference published no transformer numbers
        "model": f"decoder-only L{L} d{D} h{H} vocab{V}",
        "n_params": n_params,
        "seq_len": S,
        "batch_per_chip": b,
        "steps": steps,
        "n_chips": n_chips,
        "step_time_ms": round(st * 1e3, 2),
        "device_kind": devices[0].device_kind,
        "flops_per_token": fpt,
        "flops_source": flops_source,
        "achieved_flops_per_chip": round(achieved, 2),
        "peak_bf16_flops": peak,
        "mfu": mfu,
        "tiny_smoke": tiny,
        "batch_sweep_tokens_per_sec_chip": sweep,
    }
    if mfu is not None and mfu > 1.0:
        out["suspect"] = True

    # headline first: the consumer parses the LAST stdout line, so if the
    # optional A/B below is killed mid-run (timeout/OOM) this line is the
    # row of record — the A/B can only enrich, never sink it
    print(json.dumps(out), flush=True)

    if "--sparse" in sys.argv:
        # block-sparse FFN A/B (docs/performance.md §Block-sparse FFN):
        # dense control vs BLaST schedule (dense warmup -> magnitude
        # block pruning to target sparsity), SAME data/seed/step count.
        # Reports MFU-vs-dense at the final sparsity plus the
        # loss-vs-sparsity trajectory.  Runs on the CPU tiny smoke too —
        # the interpret-mode kernel is the same code path Mosaic compiles.
        try:
            out["sparse"] = _sparse_ab(
                b, tiny, n_chips, mesh, crit, rng, V, S, L, D, H,
                fpt, peak)
        except Exception as e:  # noqa: BLE001 — enrich, never sink
            out["sparse_error"] = f"{type(e).__name__}: {e}"[:300]
        print(json.dumps(out), flush=True)

    prior_flash = os.environ.get("BIGDL_TPU_FLASH")
    if (on_tpu and not tiny and prior_flash != "0"
            and os.environ.get("BENCH_LM_AB", "1") != "0"):
        # flash-vs-XLA A/B at the winning batch: the MHA layers auto-
        # select the Pallas kernel on TPU; BIGDL_TPU_FLASH=0 re-traces
        # through XLA attention.  Records the honest comparison the
        # kernel layer must win to stay the default (VERDICT r4 item 2).
        # Skipped when the operator already demoted the kernel (the
        # headline would itself be the XLA path — nothing to compare).
        try:
            os.environ["BIGDL_TPU_FLASH"] = "0"
            tps_xla, st_xla = measure(b)
            out["tokens_per_sec_chip_xla_attention"] = round(tps_xla, 1)
            out["flash_vs_xla_speedup"] = round(tps / tps_xla, 3)
        except Exception as e:
            out["xla_attention_ab_error"] = f"{type(e).__name__}: {e}"[:200]
        finally:
            if prior_flash is None:
                os.environ.pop("BIGDL_TPU_FLASH", None)
            else:
                os.environ["BIGDL_TPU_FLASH"] = prior_flash
        print(json.dumps(out))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
