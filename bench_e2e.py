"""End-to-end HOST-FED training bench — prints ONE JSON line.

VERDICT r4 Missing #3: the number of record is device-resident synthetic
input; the claim that steady-state training overlaps input DMA rested on
CPU-only tests of ``data/prefetch.py``.  This runs the REAL
``Optimizer.optimize()`` loop — ``RecordDataSet`` (BTRECv1 mmap gather) →
``thread_prefetch`` (host lookahead) → ``prefetch_to_device`` (DMA
double-buffer) → ``ShardedParameterStep`` — on the actual device and
reports how close host-fed steady state comes to the device-resident step.

Reference analog: ``DistriOptimizer.scala`` measured throughput end-to-end
over ``RDD[Sample]``, never on synthetic device-resident tensors.

Protocol (tunnel-aware: images ship uint8, normalization runs ON DEVICE in
a ``Lambda`` head, so the per-step transfer is 4x smaller than f32):

- steady-state step time by difference: ``T(warm+N) - T(warm)`` over two
  ``optimize()`` runs (both pay init + cached compile; the difference is
  N steady iterations).
- device-resident comparator: same model/batch via ``ShardedParameterStep``
  on a pre-sharded batch (bench.py's measure protocol).
- verdict field ``hostfed_ratio`` = hostfed_step / device_step;
  overlap works when <= ~1.3 at tunnel-feasible geometry.
- plus the loader THREAD-SCALING curve (VERDICT r4 Weak #3) on whatever
  cores exist.

Env knobs: ``E2E_HW`` (default 160), ``E2E_BATCH`` per chip (128),
``E2E_STEPS`` (24), ``E2E_RECORDS`` (2048), ``E2E_TRACE=1`` attaches the
xplane summary of a short host-fed window.
"""

import json
import os
import tempfile
import time

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))

HW = int(os.environ.get("E2E_HW", "160"))
BATCH = int(os.environ.get("E2E_BATCH", "128"))
STEPS = int(os.environ.get("E2E_STEPS", "24"))
RECORDS = int(os.environ.get("E2E_RECORDS", "2048"))
WARM = 3
CLASSES = 100


def main():
    import jax

    # this image's axon plugin ignores the JAX_PLATFORMS *env var*; honor
    # it here so CPU smokes don't hang on a down TPU tunnel
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")

    from bigdl_tpu.runtime.engine import enable_compile_cache

    enable_compile_cache(os.path.join(HERE, ".jax_cache"))

    import jax.numpy as jnp

    from bigdl_tpu.data.records import RecordDataSet, write_records
    from bigdl_tpu.models.resnet import resnet50
    from bigdl_tpu.nn.criterion import CrossEntropyCriterion
    from bigdl_tpu.nn.module import Lambda, Sequential
    from bigdl_tpu.optim.optim_method import SGD
    from bigdl_tpu.optim.optimizer import Optimizer
    from bigdl_tpu.optim.train_step import ShardedParameterStep
    from bigdl_tpu.optim.trigger import Trigger
    from bigdl_tpu.runtime.mesh import MeshSpec, build_mesh

    devices = jax.devices()
    on_tpu = devices[0].platform == "tpu"
    n_chips = len(devices)
    hw, batch_chip, steps, records = HW, BATCH, STEPS, RECORDS
    if not on_tpu:  # CPU smoke: harness check only, never evidence
        hw, batch_chip, steps, records = 32, 8, 4, 64
    batch = batch_chip * n_chips

    mean = jnp.asarray([0.485, 0.456, 0.406], jnp.float32) * 255.0
    std = jnp.asarray([0.229, 0.224, 0.225], jnp.float32) * 255.0

    def normalize(x):
        # uint8 NHWC → normalized f32 on device: the host ships 1/4 the
        # bytes and the cast fuses into the stem conv's prologue
        return (x.astype(jnp.float32) - mean) / std

    def make_model():
        return Sequential([Lambda(normalize, name="normalize"),
                           resnet50(classes=CLASSES, stem="conv")])

    criterion = CrossEntropyCriterion()

    rs = np.random.RandomState(0)
    out = {
        "metric": "resnet50_e2e_hostfed_throughput",
        "unit": "images/sec/chip",
        "vs_baseline": None,
        "live": True,
        "platform": devices[0].platform,
        "device_kind": devices[0].device_kind,
        "n_chips": n_chips,
        "image_size": hw,
        "batch_per_chip": batch_chip,
        "steps": steps,
        "records": records,
        "input_dtype": "uint8",
    }
    if not on_tpu:
        out["tiny_smoke"] = True

    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "e2e.btrec")
        xs = rs.randint(0, 255, (records, hw, hw, 3), np.uint8)
        ys = rs.randint(0, CLASSES, (records,)).astype(np.int32)
        write_records(path, {"x": xs, "y": ys})

        def run_optimize(n_iters):
            ds = RecordDataSet(path, feature="x", label="y")
            try:
                opt = Optimizer(make_model(), ds, criterion,
                                batch_size=batch, seed=7)
                opt.set_optim_method(
                    SGD(learning_rate=0.05, momentum=0.9))
                opt.set_end_when(Trigger.max_iteration(n_iters))
                opt.log_every = max(n_iters, 1)
                opt.host_prefetch = 2
                opt.prefetch = 2
                t0 = time.perf_counter()
                opt.optimize()
                return time.perf_counter() - t0
            finally:
                ds.close()

        # untimed prewarm populates the compile caches — WITHOUT it the
        # first timed run pays full compilation while the second hits the
        # cache, and the difference estimator goes negative
        t_compile = run_optimize(1)
        t_warm = run_optimize(WARM)
        t_full = run_optimize(WARM + steps)
        hostfed_step = (t_full - t_warm) / steps
        out["hostfed_step_ms"] = round(hostfed_step * 1e3, 2)
        out["warm_s"] = round(t_warm, 2)
        out["compile_s"] = round(t_compile, 2)
        if hostfed_step <= 0 or not np.isfinite(hostfed_step):
            # difference estimator degenerated (non-steady caches or too
            # few steps): the row must not be publishable evidence
            out["suspect"] = True
            out["value"] = 0.0
        else:
            out["value"] = round(batch / hostfed_step / n_chips, 2)

        # ---- device-resident comparator (bench.py protocol) -------------
        mesh = build_mesh(MeshSpec(), devices=devices)
        model = make_model()
        rng = jax.random.PRNGKey(0)
        xb, yb = xs[:batch], ys[:batch]
        variables = model.init(rng, jnp.asarray(xb[:1]))
        step = ShardedParameterStep(
            model, criterion, SGD(learning_rate=0.05, momentum=0.9),
            mesh, variables)
        x_dev, y_dev = step.shard_batch(xb), step.shard_batch(yb)
        loss = step.train_step_device(0, rng, x_dev, y_dev)
        float(np.asarray(loss))  # warm: compile + value fetch
        t0 = time.perf_counter()
        for i in range(steps):
            loss = step.train_step_device(i + 1, rng, x_dev, y_dev)
        final = float(np.asarray(loss))
        device_step = (time.perf_counter() - t0) / steps
        assert np.isfinite(final), final
        out["device_step_ms"] = round(device_step * 1e3, 2)
        out["img_per_sec_chip_device"] = round(
            batch / device_step / n_chips, 2)
        if hostfed_step > 0:
            out["hostfed_ratio"] = round(hostfed_step / device_step, 3)
            # input-stall estimate: the fraction of host-fed step time the
            # device spent waiting on input (0 when overlap hides it all)
            out["input_stall_fraction"] = round(
                max(0.0, 1.0 - device_step / hostfed_step), 4)
            out["overlap_ok"] = bool(out["hostfed_ratio"] <= 1.3)

        if on_tpu and os.environ.get("E2E_TRACE") == "1":
            try:
                from bench import _trace_summary

                trace_dir = os.path.join(HERE, "profile_e2e_r05")
                ds = RecordDataSet(path, feature="x", label="y")
                try:
                    with jax.profiler.trace(trace_dir):
                        opt = Optimizer(make_model(), ds, criterion,
                                        batch_size=batch, seed=7)
                        opt.set_optim_method(
                            SGD(learning_rate=0.05, momentum=0.9))
                        opt.set_end_when(Trigger.max_iteration(4))
                        opt.log_every = 4
                        opt.host_prefetch = 2
                        opt.prefetch = 2
                        opt.optimize()
                finally:
                    ds.close()
                out["profile"] = _trace_summary(trace_dir)
            except Exception as e:
                out["profile"] = {"error": f"{type(e).__name__}: {e}"[:300]}

    # ---- loader thread-scaling curve (Weak #3) --------------------------
    try:
        from bench_loader import measure_loader

        cores = os.cpu_count() or 1
        threads = sorted(t for t in {1, 2, 4, 8, cores} if t <= cores)
        curve = {}
        for t in threads:
            r = measure_loader(batch=256, n_batches=2, threads=t)
            curve[str(t)] = r.get("loader_img_per_sec")
        out["loader_thread_scaling"] = {"host_cores": cores, "curve": curve}
    except Exception as e:
        out["loader_thread_scaling"] = {
            "error": f"{type(e).__name__}: {e}"[:200]}

    print(json.dumps(out))


if __name__ == "__main__":
    main()
