"""Recsys serving bench — the RECSYS_r*.json evidence source
(docs/recsys.md §Bench geometry).

One run covers the full new-workload vertical:

1. **Features + training**: a string-keyed interaction log goes through
   ``ShardedFeatureTable.gen_string_idx`` (4 partitions) and the vocab is
   checked IDENTICAL to the single-host ``FeatureTable`` twin before it
   feeds a TwoTower trained with the in-batch-softmax step; a
   ``TCNForecaster`` trains through the declarative GSPMD driver
   (``fit(parallelism="dp")``) and an ``AutoformerForecaster`` through
   the classic ZeRO-1 path — the Friesian + Chronos pair the BigDL 2.0
   paper ships as flagship workloads.
2. **Sharded-serving parity**: the SAME checkpoint serves through two
   :class:`~bigdl_tpu.friesian.pipeline.RecommendationPipeline`\\ s —
   unsharded and ``layout="fsdp:2,tp:4"`` vocab-sharded — and the run
   FAILS unless recall candidate ids match exactly and ranked scores
   match to float tolerance (the MLP contraction dims are mesh-sharded,
   so score bits may differ in reduction order; ``scores_byte_equal``
   records the measured truth), and unless per-chip embedding-table
   bytes shrink by >= the mesh model-shard factor.
3. **Sustained mixed-tenant load**: keep-alive clients drive
   ``POST /recommend`` (mixed k) against the sharded pipeline through
   :class:`HttpFrontend` with the recompile sentinel STEADY — the run
   fails on any client error or any unexpected XLA recompile.  Reports
   recommend QPS + p50/p99 and the recall stage's raw candidate
   throughput; the per-axis lookup-collective bytes ride the artifact.

Output: one JSON row on the last stdout line (the sentinel
``_load_fresh`` contract) with ``bench="recsys"`` — the
``recsys_qps`` / ``recsys_recommend_p99_ms`` /
``recsys_recall_candidates_per_s`` families the perf-regression
sentinel gates against the committed RECSYS_r* trajectory.

CLI::

    python bench_recsys.py                   # full run
    python bench_recsys.py --smoke           # CI gate: tiny geometry,
                                             # parity + zero recompiles
    python bench_recsys.py --out RECSYS_r01.json
"""

import os

# 8 virtual CPU devices BEFORE jax initializes (same discipline as
# tests/conftest.py); the env var must precede the first jax import
os.environ["XLA_FLAGS"] = ("--xla_force_host_platform_device_count=8 "
                           + os.environ.get("XLA_FLAGS", ""))

import argparse
import json
import sys
import threading
import time

import numpy as np

import jax

# this image's jax build ignores JAX_PLATFORMS; the config update is
# what actually forces CPU (see tests/conftest.py)
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)

REPO = os.path.dirname(os.path.abspath(__file__))

LAYOUT = "fsdp:2,tp:4"          # 8 chips, model-shard factor 8
SHARD_FACTOR = 8
HIST_LEN = 8
K_CANDIDATES = 64


def _pct(xs, q: float) -> float:
    xs = np.sort(np.asarray(xs, np.float64))
    if xs.size == 0:
        return 0.0
    return float(xs[int(q * (xs.size - 1))])


# ---------------------------------------------------------------------------
# phase 1: sharded feature engineering -> TwoTower; forecasters
# ---------------------------------------------------------------------------


def build_features(n_users: int, n_items: int, n_rows: int):
    """String-keyed interaction log -> (vocab-parity dict, encoded ids,
    per-user histories).  The vocab comes from the SHARDED path and is
    asserted identical to the single-host twin — the distributed feature
    layer feeding the exact same training step."""
    import pandas as pd

    from bigdl_tpu.friesian.sharded import ShardedFeatureTable
    from bigdl_tpu.friesian.table import FeatureTable

    rs = np.random.RandomState(7)
    u_col = [f"u{rs.randint(n_users):04d}" for _ in range(n_rows)]
    i_col = [f"i{int(rs.zipf(1.3)) % n_items:05d}" for _ in range(n_rows)]
    # coverage tail: every user/item string appears at least once, so the
    # vocab sizes are exactly n+1 (OOV slot 0) — chosen divisible by the
    # mesh model-shard factor, a hard requirement for vocab-dim sharding
    tail = max(n_users, n_items)
    u_col += [f"u{j % n_users:04d}" for j in range(tail)]
    i_col += [f"i{j % n_items:05d}" for j in range(tail)]
    df = pd.DataFrame({"user": u_col, "item": i_col})
    sharded = ShardedFeatureTable.partition(df, 4)
    u_idx, i_idx = sharded.gen_string_idx(["user", "item"])
    su_idx, si_idx = FeatureTable(df).gen_string_idx(["user", "item"])
    vocab_parity = {"user": u_idx.mapping == su_idx.mapping,
                    "item": i_idx.mapping == si_idx.mapping}
    users = u_idx.encode(df["user"])
    items = i_idx.encode(df["item"])
    hists = {}
    for u, i in zip(users, items):
        hists.setdefault(int(u), []).append(int(i))
    return vocab_parity, users, items, hists, u_idx.size, i_idx.size


def train_two_tower_sgd(users, items, hists, n_users: int, n_items: int,
                        dim: int, iters: int, batch: int = 64):
    """The in-batch sampled-softmax step over (user, hist, positive item)
    rows — the standard two-tower objective, plain-SGD on the jit'd
    value_and_grad step (the test_friesian_serving training idiom)."""
    import jax.numpy as jnp

    from bigdl_tpu.models.recsys import TwoTower

    tt = TwoTower(n_users=n_users, n_items=n_items, dim=dim, hidden=(32,))
    rng = jax.random.PRNGKey(0)
    hist_mat = np.zeros((n_users, HIST_LEN), np.int64)
    for u, h in hists.items():
        h = h[-HIST_LEN:]
        hist_mat[u, :len(h)] = h
    params, _ = tt.build(rng, np.zeros((2,), np.int32),
                         np.zeros((2, HIST_LEN), np.int32),
                         np.zeros((2,), np.int32))

    @jax.jit
    def step(params, u, h, i):
        def loss_fn(p):
            logits, _ = tt.forward(p, None, u, h, i)
            labels = jnp.arange(logits.shape[0])
            logp = jax.nn.log_softmax(logits, axis=-1)
            return -jnp.mean(jnp.take_along_axis(
                logp, labels[:, None], axis=1))
        loss, grads = jax.value_and_grad(loss_fn)(params)
        params = jax.tree_util.tree_map(
            lambda p, g: p - 0.1 * g, params, grads)
        return params, loss

    rs = np.random.RandomState(1)
    loss = None
    t0 = time.time()
    for _ in range(iters):
        sel = rs.randint(0, len(users), batch)
        u = users[sel].astype(np.int32)
        i = items[sel].astype(np.int32)
        params, loss = step(params, u, hist_mat[u], i)
    return (tt, {k: np.asarray(v) for k, v in params.items()}, hist_mat,
            float(loss), time.time() - t0)


def train_forecasters(smoke: bool) -> dict:
    """TCN through the declarative GSPMD driver (the satellite's
    ``parallelism=`` carry), Autoformer through the classic path."""
    from bigdl_tpu.forecast.forecaster import (
        AutoformerForecaster, TCNForecaster,
    )

    rs = np.random.RandomState(3)
    n, lookback, horizon = (32, 16, 4) if smoke else (64, 24, 4)
    t = np.cumsum(rs.randn(n, lookback + horizon, 1), axis=1) \
        .astype(np.float32)
    x, y = t[:, :lookback], t[:, lookback:]

    out = {}
    tcn = TCNForecaster(lookback, horizon, 1, 1,
                        num_channels=(8, 8), kernel_size=3, dropout=0.0)
    t0 = time.time()
    tcn.fit((x, y), epochs=1, batch_size=16, parallelism="dp")
    out["tcn"] = {
        "parallelism": "dp",
        "train_time_s": round(time.time() - t0, 2),
        "final_loss": round(float(tcn._layout_stats["losses"][-1]), 5),
        "mesh": tcn._layout_stats["mesh"],
        "mse": round(float(tcn.evaluate((x, y))["mse"]), 5),
    }

    af = AutoformerForecaster(lookback, horizon, 1, 1, d_model=16,
                              n_heads=2, e_layers=1, d_layers=1, d_ff=32)
    t0 = time.time()
    af.fit((x, y), epochs=1, batch_size=16)
    out["autoformer"] = {
        "parallelism": None,
        "train_time_s": round(time.time() - t0, 2),
        "mse": round(float(af.evaluate((x, y))["mse"]), 5),
    }
    return out


# ---------------------------------------------------------------------------
# phase 2: pipelines, parity, sustained /recommend load
# ---------------------------------------------------------------------------


def build_pipelines(tt, params, hist_mat, n_users: int):
    from bigdl_tpu.friesian.pipeline import RecommendationPipeline
    from bigdl_tpu.friesian.serving import FeatureService

    pipes = []
    for layout in (None, LAYOUT):
        fs = FeatureService()
        p = RecommendationPipeline(
            tt, params, fs, hist_len=HIST_LEN, k_candidates=K_CANDIDATES,
            layout=layout, batch_buckets=(1, 4, 16, 64))
        for u in range(n_users):
            p.put_user_history(u, hist_mat[u][hist_mat[u] > 0])
        p.start()
        p.warmup()
        pipes.append(p)
    return pipes


def check_parity(plain, sharded, n_probe: int) -> dict:
    ids_equal = True
    byte_equal = True
    max_diff = 0.0
    for u in range(n_probe):
        s1, i1 = plain.recall_only(u)
        s2, i2 = sharded.recall_only(u)
        ids_equal &= bool(np.array_equal(i1, i2))
        byte_equal &= bool(np.array_equal(s1, s2))
        max_diff = max(max_diff, float(np.max(np.abs(s1 - s2))))
        r1 = plain.recommend(u, k=10)
        r2 = sharded.recommend(u, k=10)
        ids_equal &= [i for i, _ in r1] == [i for i, _ in r2]
        byte_equal &= all(a[1] == b[1] for a, b in zip(r1, r2))
        max_diff = max(max_diff, max(
            (abs(a[1] - b[1]) for a, b in zip(r1, r2)), default=0.0))
    unsharded_bytes = plain.param_bytes_per_chip()
    sharded_bytes = sharded.param_bytes_per_chip()
    factor = {k: unsharded_bytes[k] / max(sharded_bytes[k], 1)
              for k in unsharded_bytes}
    return {
        "candidate_ids_equal": ids_equal,
        "scores_byte_equal": byte_equal,
        "score_max_abs_diff": max_diff,
        "param_bytes_unsharded": unsharded_bytes,
        "param_bytes_per_chip": sharded_bytes,
        "embedding_shard_factor": min(factor.values()) if factor else 0.0,
    }


def run_load(pipe, n_users: int, clients: int, duration_s: float):
    """Keep-alive clients drive POST /recommend (mixed k) through the
    HTTP frontend against the mesh-sharded pipeline."""
    from bigdl_tpu.serving.http_frontend import HttpClient, HttpFrontend

    fe = HttpFrontend(pipe.server, port=0,
                      recsys_pipeline=pipe).start()
    lats, errors = [], []
    stop_t = [0.0]

    def client(seed: int):
        c = HttpClient(fe.url, keep_alive=True)
        rs = np.random.RandomState(seed)
        while time.time() < stop_t[0]:
            u = int(rs.randint(n_users))
            k = int(rs.choice([3, 5, 10]))
            t0 = time.time()
            try:
                items = c.recommend(u, k=k)
                if len(items) != k:
                    raise RuntimeError(
                        f"recommend returned {len(items)} items, want {k}")
            except Exception as e:  # noqa: BLE001 — counted, run fails
                errors.append(repr(e))
                return
            lats.append(time.time() - t0)

    try:
        # warm phase outside the window: handler threads + client conns
        stop_t[0] = time.time() + min(0.6, duration_s)
        warm = [threading.Thread(target=client, args=(100 + i,))
                for i in range(clients)]
        for t in warm:
            t.start()
        for t in warm:
            t.join()
        lats.clear()
        t0 = time.time()
        stop_t[0] = t0 + duration_s
        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.time() - t0
    finally:
        fe.stop()
    return lats, errors, wall


def measure_recall_throughput(pipe, n_users: int, iters: int) -> float:
    """Raw recall-stage candidate throughput: full-bucket batches through
    the recall InferenceModel (candidates surfaced per second)."""
    rows = np.stack([pipe._user_row(u % n_users) for u in range(64)]) \
        .astype(np.float32)
    pipe.recall_model.predict(rows)  # ensure compiled/placed
    t0 = time.time()
    for _ in range(iters):
        pipe.recall_model.predict(rows)
    dt = time.time() - t0
    return 64 * iters * pipe.k_candidates / dt


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="recsys serving bench (docs/recsys.md)")
    ap.add_argument("--smoke", action="store_true",
                    help="CI gate: tiny geometry, parity + zero "
                         "unexpected recompiles")
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--duration", type=float, default=4.0)
    ap.add_argument("--out", default=None,
                    help="also write the artifact JSON here")
    args = ap.parse_args(argv)

    from bigdl_tpu.obs.attr import recompile_sentinel
    from bigdl_tpu.optim.metrics import global_metrics

    sent = recompile_sentinel().install()
    smoke = args.smoke
    # vocab size is n+1 (OOV slot 0) and must divide by the mesh
    # model-shard factor 8 for vocab-dim sharding -> sizes 24/96, 48/256
    n_users, n_items, n_rows = (23, 95, 600) if smoke else (47, 255, 3000)
    clients = 4 if smoke else args.clients
    duration = 1.5 if smoke else args.duration
    failures = []

    # -- phase 1: features + training --------------------------------------
    vocab_parity, users, items, hists, u_size, i_size = build_features(
        n_users, n_items, n_rows)
    if not all(vocab_parity.values()):
        failures.append(f"sharded vocab != single-host vocab: "
                        f"{vocab_parity}")
    # StringIndex ids start at 1 (slot 0 = OOV) -> table sizes come from
    # the vocab, not the raw generator counts
    n_users, n_items = u_size, i_size
    tt, params, hist_mat, tt_loss, tt_time = train_two_tower_sgd(
        users, items, hists, n_users=n_users, n_items=n_items,
        dim=16, iters=30 if smoke else 150)
    forecast = train_forecasters(smoke)

    # -- phase 2: pipelines + parity ---------------------------------------
    plain, sharded = build_pipelines(tt, params, hist_mat, n_users)
    parity = check_parity(plain, sharded, n_probe=4 if smoke else 8)
    if not parity["candidate_ids_equal"]:
        failures.append("sharded vs unsharded recommend returned "
                        "DIFFERENT candidate ids")
    if parity["score_max_abs_diff"] > 1e-4:
        failures.append(
            f"sharded score drift {parity['score_max_abs_diff']} above "
            "float-reduction tolerance 1e-4")
    if parity["embedding_shard_factor"] < SHARD_FACTOR:
        failures.append(
            f"per-chip embedding bytes shrank only "
            f"{parity['embedding_shard_factor']}x "
            f"(< mesh model-shard factor {SHARD_FACTOR})")

    # -- phase 3: sustained mixed-k load, sentinel steady -------------------
    m = global_metrics()
    before = m.counter("train.unexpected_recompiles_total")
    sent.mark_steady()
    try:
        lats, errors, wall = run_load(sharded, n_users, clients, duration)
        cand_per_s = measure_recall_throughput(
            sharded, n_users, iters=5 if smoke else 25)
    finally:
        sent.mark_warmup()
    recompiles = int(m.counter("train.unexpected_recompiles_total")
                     - before)
    if errors:
        failures.append(f"{len(errors)} client errors: {errors[0]}")
    if not lats:
        failures.append("no completed /recommend requests in the window")
    if recompiles != 0:
        failures.append(f"{recompiles} unexpected XLA recompiles under "
                        "the mixed-k recommend load")

    lookup = sharded.lookup_collective_bytes()
    plain.stop()
    sharded.stop()

    row = {
        "bench": "recsys",
        "geometry": f"recsys_c{clients}_{LAYOUT.replace(',', '_').replace(':', '')}",
        "layout": LAYOUT,
        "concurrent_clients": clients,
        "duration_s": round(wall, 2),
        "requests": len(lats),
        "recsys_qps": round(len(lats) / wall, 1) if wall else 0.0,
        "recommend_p50_ms": round(_pct(lats, 0.50) * 1e3, 2),
        "recommend_p99_ms": round(_pct(lats, 0.99) * 1e3, 2),
        "recall_candidates_per_s": round(cand_per_s, 1),
        "k_candidates": K_CANDIDATES,
        "hist_len": HIST_LEN,
        "n_users": n_users,
        "n_items": n_items,
        "unexpected_recompiles": recompiles,
        "vocab_parity": vocab_parity,
        "parity": parity,
        "lookup_collective_bytes": lookup,
        "two_tower": {"iters": 30 if smoke else 150,
                      "final_loss": round(tt_loss, 5),
                      "train_time_s": round(tt_time, 2)},
        "forecast": forecast,
        "keep_alive_clients": True,
    }
    if smoke:
        row["smoke"] = True
    out = args.out
    if out is None and os.environ.get("BIGDL_TPU_WRITE_ARTIFACTS"):
        out = os.path.join(REPO, "RECSYS_r01.json")
    if out and not smoke:
        with open(out, "w") as f:
            json.dump(row, f, indent=1)
    print(json.dumps(row))
    if failures:
        print("FAIL: " + "; ".join(failures), file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
