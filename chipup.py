"""THE session evidence orchestrator — the repo's single watcher entry point.

Round-4 postmortem (VERDICT r4 Weak #7): two watchers (bench_watch.py +
chipup_r04.py) ran concurrently and double-appended the evidence trail.
This file replaces both.  Guarantees:

- SINGLE INSTANCE: an exclusive ``flock`` on ``chipup.lock`` held for the
  process lifetime; a second launch exits immediately with a log line.
- ATOMIC ARTIFACTS: every JSON artifact is written tmp-then-``os.replace``.
- REPLACE, NOT RATCHET (advisor r4 medium): a newer non-suspect live bench
  row REPLACES ``BENCH_r05.json`` even if its value is lower — full history
  stays in ``BENCH_attempts.jsonl``; a regression must be visible.

Loop: probe the tunneled chip every ``CHIPUP_INTERVAL`` s (default 390 —
the chip has been up for minutes per 12 h session; probes must be dense).
Every probe/run appends one JSON line to ``BENCH_attempts.jsonl``.

On the FIRST successful probe, run the full sequence, most valuable first,
each in its own subprocess so one hang cannot sink the rest:

1. ``bench.py --worker tpu``  no-sweep FIRST -> BENCH_r05.json banked
   (the chip has died minutes into a window; a sweep timeout must never
   cost the round its only snapshot), then the sweep+trace upgrade pass
2. ``bench_lm.py``                           -> BENCH_LM_r05.json
3. ``kernels_selfcheck.py``   (amortized)    -> KERNELS_r05.json (all_ok only)
4. ``bench_e2e.py``           (host-fed)     -> BENCH_E2E_r05.json
5. ``bench_probe.py``         (breakdown)    -> PROBE_r05.json
6. ``dryrun_tpu_ops``         (Mosaic proof) -> PALLAS_TPU_r05.json

On LATER windows: re-run whatever is missing/failed, plus a quick
(no-sweep) bench refresh whose good rows replace the snapshot.
``CHIPUP_REPEAT=1`` forces the full sequence every window.

Run detached at session start:  ``nohup python chipup.py >> chipup.log &``
"""

import fcntl
import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
# env overrides exist so tests can exercise the lock/sequence machinery
# without touching the session's real evidence trail or artifacts
ATTEMPTS = os.environ.get("CHIPUP_ATTEMPTS",
                          os.path.join(HERE, "BENCH_attempts.jsonl"))
LOCK = os.environ.get("CHIPUP_LOCK", os.path.join(HERE, "chipup.lock"))
_ART = os.environ.get("CHIPUP_ARTIFACT_DIR", HERE)
BENCH = os.path.join(_ART, "BENCH_r05.json")
LM = os.path.join(_ART, "BENCH_LM_r05.json")
KERNELS = os.path.join(_ART, "KERNELS_r05.json")
E2E = os.path.join(_ART, "BENCH_E2E_r05.json")
PROBE = os.path.join(_ART, "PROBE_r05.json")
PALLAS = os.path.join(_ART, "PALLAS_TPU_r05.json")

INTERVAL = float(os.environ.get("CHIPUP_INTERVAL", "390"))
PROBE_TIMEOUT = float(os.environ.get("CHIPUP_PROBE_TIMEOUT", "150"))

_PROBE_SRC = (
    "import jax, json; d = jax.devices()[0]; "
    "print(json.dumps({'platform': d.platform, "
    "'device_kind': d.device_kind}))"
)


def _log(entry):
    entry["ts"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    with open(ATTEMPTS, "a") as f:
        f.write(json.dumps(entry) + "\n")
    print(json.dumps(entry), flush=True)


def _atomic_write(path, obj):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f, indent=1)
    os.replace(tmp, path)


def _acquire_lock():
    """Exclusive non-blocking flock; the fd must stay open for process
    lifetime.  Returns the fd or None if another instance holds it."""
    fd = os.open(LOCK, os.O_CREAT | os.O_RDWR, 0o644)
    try:
        fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
    except OSError:
        os.close(fd)
        return None
    os.ftruncate(fd, 0)
    os.write(fd, f"{os.getpid()}\n".encode())
    return fd


_LEGACY_WATCHERS = ("bench_watch.py", "chipup_r04.py")


def _kill_stray_legacy_watchers():
    """The flock stops a second chipup.py, but a watcher from a PREVIOUS
    session (round 4's script, already deleted from the repo but still
    loaded in a live process) predates the lock.  Found live at 22:26 on
    2026-08-01 — sweep them at startup and log it.

    Anchored to THIS repo: only processes whose cwd is HERE (or whose
    cmdline names a script under HERE) are touched — a sibling checkout's
    watcher is not ours to kill.  CHIPUP_STRAY_SWEEP=0 disables (tests)."""
    if os.environ.get("CHIPUP_STRAY_SWEEP", "1") == "0":
        return
    me = os.getpid()
    for pid in os.listdir("/proc"):
        if not pid.isdigit() or int(pid) == me:
            continue
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                cmd = f.read().decode(errors="replace").replace("\0", " ")
        except OSError:
            continue
        if "python" not in cmd or not any(w in cmd
                                          for w in _LEGACY_WATCHERS):
            continue
        try:
            cwd = os.readlink(f"/proc/{pid}/cwd")
        except OSError:
            cwd = ""
        if cwd != HERE and (HERE + "/") not in cmd:
            continue
        try:
            os.kill(int(pid), 15)
            _log({"kind": "stray_watcher_killed", "pid": int(pid),
                  "cwd": cwd, "cmd": cmd.strip()[:120]})
        except OSError:
            pass


def _probe():
    try:
        r = subprocess.run([sys.executable, "-c", _PROBE_SRC], cwd=HERE,
                           capture_output=True, text=True,
                           timeout=PROBE_TIMEOUT)
    except subprocess.TimeoutExpired:
        return False, f"probe timed out after {PROBE_TIMEOUT:.0f}s"
    if r.returncode == 0 and r.stdout.strip():
        try:
            info = json.loads(r.stdout.strip().splitlines()[-1])
        except json.JSONDecodeError:
            return False, "unparseable probe output"
        if info.get("platform") == "tpu":
            return True, info
        return False, f"backend is {info.get('platform')!r}, not tpu"
    return False, (r.stderr or r.stdout or "")[-200:]


def _run(argv, timeout, env=None):
    e = dict(os.environ, **(env or {}))
    try:
        r = subprocess.run(argv, cwd=HERE, capture_output=True, text=True,
                           timeout=timeout, env=e)
        return r.returncode, r.stdout, r.stderr
    except subprocess.TimeoutExpired:
        return -1, "", f"timed out after {timeout:.0f}s"


def _last_json(stdout):
    lines = [ln for ln in stdout.strip().splitlines() if ln.strip()]
    if not lines:
        return None
    try:
        return json.loads(lines[-1])
    except json.JSONDecodeError:
        return None


def _merge_bench(row):
    """Replace-not-ratchet: any good live row becomes the snapshot.  The
    replaced row's FULL contents are appended to the trail first, so
    nothing measured ever exists nowhere.  With no good snapshot on disk,
    even a not-good live row is written (suspect flags intact) — a flagged
    measurement beats zero evidence (bench_watch's documented behavior)."""
    from bench import is_good_row

    if row is None:
        _log({"kind": "bench", "ok": False, "error": "unparseable stdout"})
        return False
    prev = None
    if os.path.exists(BENCH):
        try:
            with open(BENCH) as f:
                prev = json.load(f)
        except Exception:
            pass
    good = is_good_row(row) and row.get("live")
    if not good:
        if prev is not None and is_good_row(
                prev.get("parsed") if isinstance(prev.get("parsed"), dict)
                else prev):
            _log({"kind": "bench_rejected", "value": row.get("value"),
                  "mfu": row.get("mfu"), "suspect": bool(row.get("suspect")),
                  "live": bool(row.get("live"))})
            return False
        # no good snapshot exists: flagged evidence beats none
        row.setdefault("suspect", True)
    if prev is not None:
        # full-history invariant: the replaced snapshot goes to the trail
        _log({"kind": "bench_replaced_row", "row": prev})
    row["captured_ts"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    _atomic_write(BENCH, row)
    _log({"kind": "bench", "ok": True, "good": bool(good),
          "value": row.get("value"), "mfu": row.get("mfu"),
          "batch": row.get("batch_per_chip")})
    return bool(good)


def _bench_pass(mode):
    """mode: 'bank' (lean first capture: no sweep/trace/hostfed — seconds
    matter before the chip dies), 'sweep' (the full upgrade pass), or
    'refresh' (later windows: no sweep, but trace + hostfed stay on so a
    replacing row is never poorer than the one it replaces)."""
    sweep = mode == "sweep"
    env = {"bank": {"BENCH_HOSTFED": "0"},
           "sweep": {"BENCH_SWEEP": "1", "BENCH_TRACE": "1"},
           "refresh": {"BENCH_TRACE": "1"}}[mode]
    if not sweep and os.path.exists(BENCH):
        # quick refresh must measure the snapshot's own (possibly sweep-
        # promoted) batch — refreshing at the default 768 would replace a
        # better-batch headline with a config change, not a regression
        try:
            with open(BENCH) as f:
                snap = json.load(f)
            if isinstance(snap.get("parsed"), dict):
                snap = snap["parsed"]  # round-driver {…, parsed} wrapper
            b = snap.get("batch_per_chip")
            if b:
                env["BENCH_BATCH"] = str(int(b))
        except Exception:
            pass
    base_timeout = float(os.environ.get("BENCH_TPU_TIMEOUT", "1800"))
    rc, out, err = _run([sys.executable, "bench.py", "--worker", "tpu"],
                        base_timeout * (2 if sweep else 1), env=env)
    if rc != 0:
        _log({"kind": "bench", "ok": False, "error": (err or out)[-300:]})
        return False
    return _merge_bench(_last_json(out))


def _lm_pass():
    rc, out, err = _run([sys.executable, "bench_lm.py"], 2400)
    if rc != 0:
        _log({"kind": "bench_lm", "ok": False, "error": (err or out)[-300:]})
        return False
    row = _last_json(out)
    if row is None:
        _log({"kind": "bench_lm", "ok": False, "error": "unparseable"})
        return False
    if row.get("suspect") or row.get("tiny_smoke") or not row.get("value"):
        _log({"kind": "bench_lm_rejected", "value": row.get("value"),
              "suspect": bool(row.get("suspect"))})
        return False
    row["captured_ts"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    _atomic_write(LM, row)
    _log({"kind": "bench_lm", "ok": True, "value": row.get("value"),
          "mfu": row.get("mfu")})
    return True


def _kernels_pass():
    tmp = KERNELS + ".run"
    rc, out, err = _run([sys.executable, "kernels_selfcheck.py", tmp], 1800)
    ok = rc == 0 and os.path.exists(tmp)
    salvaged = False
    if not ok and os.path.exists(tmp):
        # the selfcheck writes the artifact BEFORE its optional tiling
        # probe: a probe-induced crash/timeout (rc!=0) can leave a
        # complete, passing report — install it rather than discard it,
        # but mark the trail line so a crash-salvage is never mistaken
        # for a clean pass
        try:
            with open(tmp) as f:
                ok = salvaged = bool(json.load(f).get("all_ok"))
        except Exception:
            ok = False
    if ok:
        os.replace(tmp, KERNELS)
    elif os.path.exists(tmp):
        os.remove(tmp)
    entry = {"kind": "kernels", "ok": ok}
    if salvaged:
        entry.update(salvaged=True, rc=rc, error=(err or out)[-300:])
    elif not ok:
        entry["error"] = (err or out)[-300:]
    _log(entry)
    return ok


def _e2e_pass():
    rc, out, err = _run([sys.executable, "bench_e2e.py"], 2400,
                        env={"E2E_TRACE": "1"})
    row = _last_json(out) if rc == 0 else None
    ok = (row is not None and not row.get("error")
          and not row.get("suspect") and not row.get("tiny_smoke"))
    if ok:
        row["captured_ts"] = time.strftime("%Y-%m-%dT%H:%M:%S")
        _atomic_write(E2E, row)
    _log({"kind": "bench_e2e", "ok": ok,
          **({"value": row.get("value")} if ok
             else {"error": (err or out)[-300:]})})
    return ok


def _probe_pass():
    rc, out, err = _run([sys.executable, "bench_probe.py", "--out", PROBE],
                        1500)
    ok = rc == 0 and os.path.exists(PROBE)
    _log({"kind": "probe_breakdown", "ok": ok,
          **({} if ok else {"error": (err or out)[-300:]})})
    return ok


def _pallas_pass():
    """Mosaic on-device Pallas dryrun (__graft_entry__.dryrun_tpu_ops) —
    the L0 native-kernel evidence bench_watch used to capture."""
    src = ("import json, __graft_entry__ as g; "
           "print(json.dumps(g.dryrun_tpu_ops()))")
    rc, out, err = _run([sys.executable, "-c", src], 1800)
    row = _last_json(out) if rc == 0 else None
    ok = row is not None
    if ok:
        _atomic_write(PALLAS, row)
    _log({"kind": "pallas_dryrun", "ok": ok,
          **({} if ok else {"error": (err or out)[-300:]})})
    return ok


def main():
    fd = _acquire_lock()
    if fd is None:
        print(json.dumps({"kind": "chipup_duplicate", "pid": os.getpid(),
                          "error": "another chipup.py holds the lock"}),
              flush=True)
        return 1
    _log({"kind": "chipup_start", "pid": os.getpid(),
          "interval_s": INTERVAL})
    _kill_stray_legacy_watchers()
    done = {"bench": False, "bench_sweep": False, "lm": False,
            "kernels": False, "e2e": False, "probe": False,
            "pallas": False}
    repeat = os.environ.get("CHIPUP_REPEAT") == "1"
    while True:
        ok, info = _probe()
        _log({"kind": "probe", "ok": ok,
              **({"result": info} if ok else {"error": str(info)[-200:]})})
        if ok:
            if repeat or not done["bench"]:
                # bank a headline FIRST — the chip has died minutes into
                # a window before, and a timeout/death mid-sweep must
                # never cost the round its only snapshot.  Lean 'bank'
                # mode ONLY while no snapshot exists at all: once any row
                # is on disk (e.g. a sweep landed while the bank timed
                # out), retries use 'refresh' so a replacing row is never
                # poorer than the one it replaces.
                mode = "bank" if not os.path.exists(BENCH) else "refresh"
                done["bench"] = _bench_pass(mode) or done["bench"]
            else:
                # later windows: quick refresh (trace+hostfed on, so a
                # replacing row is never poorer); good rows replace
                _bench_pass("refresh")
            if repeat or not done["bench_sweep"]:
                # the upgrade pass retries every window until it lands,
                # and runs even if banking judged its row not-good (mfu
                # unavailable etc.) — a flagged sweep row still beats none
                done["bench_sweep"] = (_bench_pass("sweep")
                                       or done["bench_sweep"])
            if repeat or not done["lm"]:
                done["lm"] = _lm_pass() or done["lm"]
            if repeat or not done["kernels"]:
                done["kernels"] = _kernels_pass() or done["kernels"]
            if repeat or not done["e2e"]:
                done["e2e"] = _e2e_pass() or done["e2e"]
            if repeat or not done["probe"]:
                done["probe"] = _probe_pass() or done["probe"]
            if repeat or not done["pallas"]:
                done["pallas"] = _pallas_pass() or done["pallas"]
            _log({"kind": "sequence_state", **done})
        time.sleep(INTERVAL)


if __name__ == "__main__":
    sys.exit(main() or 0)
