"""Step-time breakdown probe (VERDICT r3 item 4): where do the ms go?

Times, on the real chip at the bench batch size: forward-only inference,
forward+backward gradients, and the full ShardedParameterStep, plus optional
ablations (no-BN model, alternate batch). Writes PROBE_r05.json.

Usage: python bench_probe.py [--batch 768] [--steps 8]
"""

import argparse
import json
import os
import time

import numpy as np

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".jax_cache"))

import jax

# this image's axon plugin ignores the JAX_PLATFORMS *env var*; honor
# it here so CPU smokes don't hang on a down TPU tunnel (conftest
# does the same for tests)
if os.environ.get("JAX_PLATFORMS") == "cpu":
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp


def _flops(fn, *args):
    try:
        cost = jax.jit(fn).lower(*args).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        f = float(cost.get("flops", -1))
        return f if f > 0 else None
    except Exception:
        return None


def _time(fn, args, steps):
    # BIGDL_TPU_FAULTS plans fire here too (slow_host stragglers, injected
    # step failures), so straggler/fault overhead is measurable on the
    # same harness as clean step time (docs/resilience.md).  There is no
    # recovery machinery in a raw timing loop, so RAISING faults are
    # absorbed and counted (the faulted step still costs its dispatch) —
    # the fault count rides on the returned mean via _time.faults_fired.
    from bigdl_tpu.resilience import faults

    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for i in range(steps):
        try:
            faults.fire_step(i)
        except faults.InjectedFault as e:
            _time.faults_fired += 1
            print(f"  [fault injected at step {i}: {e}]")
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / steps


_time.faults_fired = 0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=768)
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--out", default="PROBE_r05.json",
                    help="artifact path (chipup passes its redirected one)")
    args = ap.parse_args()

    from bigdl_tpu.models.resnet import resnet50
    from bigdl_tpu.nn.criterion import CrossEntropyCriterion
    from bigdl_tpu.optim.optim_method import SGD
    from bigdl_tpu.optim.train_step import ShardedParameterStep
    from bigdl_tpu.runtime.mesh import MeshSpec, build_mesh

    from bench import _RESNET50_TRAIN_FLOPS_PER_IMAGE, _peak_flops

    dev = jax.devices()[0]
    peak = _peak_flops(dev.device_kind)
    B, hw = args.batch, 224
    report = {"device_kind": dev.device_kind, "batch": B, "steps": args.steps,
              "phases": {}}

    stem = os.environ.get("BENCH_STEM", "s2d")
    report["stem"] = stem
    model = resnet50(classes=1000, stem=stem)
    rng = jax.random.PRNGKey(0)
    # generate the batch ON DEVICE: a (B,224,224,3) f32 host transfer is
    # ~0.5 GB and can wedge for minutes over the tunnel
    kx, ky = jax.random.split(rng)
    x = jax.block_until_ready(
        jax.jit(lambda k: jax.random.uniform(k, (B, hw, hw, 3)))(kx))
    y = jax.block_until_ready(
        jax.jit(lambda k: jax.random.randint(k, (B,), 0, 1000))(ky))
    variables = model.init(rng, x[:1])
    params, state = variables["params"], variables.get("state", {})
    crit = CrossEntropyCriterion()

    def fwd_train(p, s, xx):
        out, _ = model.forward(p, s, xx, training=True, rng=rng)
        return out

    def fwd_loss(p, s, xx, yy):
        out, ns = model.forward(p, s, xx, training=True, rng=rng)
        return crit.forward(out, yy), ns

    grad_fn = jax.jit(jax.grad(lambda p, s, xx, yy: fwd_loss(p, s, xx, yy)[0]))
    fwd_jit = jax.jit(fwd_train)

    def phase(name, fn, fargs, flops_fn=None, flops_args=None):
        t = _time(fn, fargs, args.steps)
        f = _flops(flops_fn or fn, *(flops_args or fargs)) if flops_fn is not False else None
        rec = {"ms": round(t * 1e3, 2),
               "img_per_sec": round(B / t, 1)}
        if f:
            rec["tflops_per_step"] = round(f / 1e12, 3)
            if peak:
                rec["mfu"] = round(f / t / peak, 4)
        report["phases"][name] = rec
        print(name, json.dumps(rec), flush=True)

    phase("fwd_only", fwd_jit, (params, state, x),
          flops_fn=fwd_train, flops_args=(params, state, x))
    phase("fwd_bwd", grad_fn, (params, state, x, y),
          flops_fn=lambda p, s, xx, yy: jax.grad(
              lambda pp: fwd_loss(pp, s, xx, yy)[0])(p),
          flops_args=(params, state, x, y))

    mesh = build_mesh(MeshSpec(), devices=jax.devices())
    step = ShardedParameterStep(
        model, crit, SGD(learning_rate=0.1, momentum=0.9, weight_decay=1e-4),
        mesh, variables)
    # x/y are already on device; device_put to the data sharding is a cheap
    # on-device relayout on one chip (no host round-trip)
    x_dev = step.shard_batch(x)
    y_dev = step.shard_batch(y)

    def full(i):
        return step.train_step_device(i, rng, x_dev, y_dev)

    # time the full engine step (device-resident inputs, value fetch at end);
    # block on the warm-up VALUE so its execution can't bleed into the window
    float(np.asarray(full(0)))
    t0 = time.perf_counter()
    for i in range(args.steps):
        loss = full(i + 1)
    float(np.asarray(loss))
    t = (time.perf_counter() - t0) / args.steps
    rec = {"ms": round(t * 1e3, 2), "img_per_sec": round(B / t, 1)}
    if peak:
        rec["mfu_analytic"] = round(
            _RESNET50_TRAIN_FLOPS_PER_IMAGE * B / t / peak, 4)
    report["phases"]["full_step"] = rec
    print("full_step", json.dumps(rec), flush=True)

    if _time.faults_fired:
        report["faults_fired"] = _time.faults_fired
    # atomic: a timeout-kill mid-dump must not leave a truncated artifact
    with open(args.out + ".tmp", "w") as f:
        json.dump(report, f, indent=1)
    os.replace(args.out + ".tmp", args.out)
    print(json.dumps({"ok": True}))


if __name__ == "__main__":
    main()
