# Test/bench entry points (CI runs these; see .github/workflows/ci.yml)

.PHONY: test test-fast test-resilience test-cluster test-serving test-decode test-quant-serving test-spec-decode test-fleet test-fleet-chaos test-obs test-slo test-data test-ingest test-bundle test-kernels test-collectives test-layout test-recsys bench bench-dispatch bench-watch bench-gradcomm bench-layout bench-decode bench-decode-quant bench-spec bench-fleet bench-fleet-chaos bench-slo bench-recsys dryrun examples bench-scaling bench-loader watch

# full suite, parallelized over cores (pytest-xdist): each worker is its
# own process with its own 8-virtual-device CPU mesh, so distribution
# tests stay isolated.  ~12.5 min serial on 1 core; -n auto cuts CI
# (2-core) wall time roughly in half.
test:
	python -m pytest tests/ -q -n auto

test-serial:
	python -m pytest tests/ -q

# the quick pre-commit loop: skips tests marked slow (multi-process
# integration + minutes-scale compile-shape checks); CI's `make test`
# still runs everything.  (The persistent compile cache is OFF by
# default — BIGDL_TPU_TEST_CACHE=1 to opt in; see tests/conftest.py for
# the segfault caveat on this image's jax build.)
test-fast:
	python -m pytest tests/ -q -x -m "not slow" -n auto

# the contributor/judge loop (VERDICT r4 item 9): the ~10-file core path,
# serial, budgeted <= 5 min warm on 1 core — covers tensor ops, layers,
# optim, the sharded train step, records, serving, storage, and the
# watcher invariant without the long tail of integration files.
CORE_TESTS = tests/test_tensor.py tests/test_nn_layers.py \
  tests/test_optim.py tests/test_distri_optimizer.py \
  tests/test_parallel.py tests/test_records.py tests/test_serving.py \
  tests/test_storage_remote.py tests/test_watcher_single.py
test-core:
	python -m pytest $(CORE_TESTS) -q

# the fault-tolerance suite (docs/resilience.md): fault injection,
# supervisor resume, elastic resume, GC-never-deletes-last-valid
test-resilience:
	python -m pytest tests/test_resilience.py tests/test_ckpt_sharded.py -q

# pod-scale coordinated fault tolerance (docs/resilience.md §Multi-host
# recovery): membership views + leader failover, partition heal, gang
# abort/rendezvous, peer-shard restore parity vs checkpoint restore,
# preemption propagation + SIGTERM step-exact resume, elastic re-sharded
# mid-epoch resume, checkpoint mirror retry.  The true 2-process
# kill/rejoin drill is a `slow` mark (add -m 'slow or not slow' locally)
test-cluster:
	python -m pytest tests/test_cluster.py tests/test_resume_exact.py -q \
	  -m "not slow"

# the serving suite (docs/serving.md): engine + frontend + pool, including
# the request-lifecycle chaos tests (worker kill, deadline expiry,
# backpressure 429s, drain-vs-drop, breaker/hedge) and the continuous-
# batching/registry/autoscaler suite (fixed-vs-continuous parity,
# deadline-aware ordering, multi-tenant SLO metrics, keep-alive reuse,
# pool autoscale up/down)
test-serving:
	python -m pytest tests/test_serving.py tests/test_serving_multiproc.py \
	  tests/test_serving_chaos.py tests/test_serving_continuous.py -q

# token-level decode serving (docs/serving.md §Autoregressive decode):
# continuous-vs-one-scan byte parity (greedy + seeded sample, mid-flight
# insertion), page-aliasing-free slot reuse, zero-recompile sweep,
# streaming chunk framing, prefill-never-stalls-decode scheduling,
# per-token deadline enforcement, paged flash-decode kernel parity
test-decode:
	python -m pytest tests/test_decode_engine.py -q

# the quantized-serving suite (docs/quantization.md §Serving memory
# hierarchy): per-page int8 quantize/dequantize bounds + monotone scale
# floors, stale-scale aliasing under slot reuse, int8-vs-f32 token
# parity budget (greedy + bounded logp drift), kernel-vs-reference
# agreement on int8 pages, weight_quant="int8" serving, the quantized
# KV handoff/migration surface, and /health page-dtype accounting
test-quant-serving:
	python -m pytest tests/test_quant_serving.py -q

# the speculative-decoding suite (docs/serving.md §Speculative
# decoding): spec-on vs spec-off byte parity (greedy + seeded sample,
# mid-flight admission), dense-twin acceptance pinned at 1.0,
# zero-recompile sweep with the draft/verify programs in the bucket
# set, spec x int8 token-parity budget, draft-page free on
# cancel/disconnect, decode_pressure honesty, and the multi-query
# verify kernel's parity with the gathered-jnp reference
test-spec-decode:
	python -m pytest tests/test_spec_decode.py -q

# the decode-fleet suite (docs/serving.md §Decode fleet): prefix-cache
# byte parity (cached-prefix vs cold prefill, greedy + seeded),
# eviction-never-frees-live-pages refcounting, KV handoff wire-format
# roundtrip + cross-engine prefill->decode parity, the KV-aware router,
# /health decode pressure + /fleet/prefill, and the pool-proxy
# prefill/decode split over real worker processes (streaming relay)
test-fleet:
	python -m pytest tests/test_fleet.py -q

# decode-fleet fault tolerance (docs/serving.md §Fleet fault tolerance):
# resume_from byte parity (re-prefill + migration adoption, greedy AND
# seeded), two-phase live drain with corrupt-handoff degradation,
# client-disconnect slot reclaim, breaker-driven snapshot invalidation,
# and — the slow pair — SIGKILL failover and scale-down drain against
# real subprocess pool workers with mid-flight streams
test-fleet-chaos:
	python -m pytest tests/test_fleet_chaos.py -q

# the observability suite (docs/observability.md): span tracer + chrome
# export, Prometheus exposition (+HELP lines, scrape-under-mutation),
# latency histograms, flight recorder under injected faults, TFRecord
# framing, profile_dir wiring, step-time attribution, live MFU/collective
# gauges, recompile sentinel, perf-regression sentinel
test-obs:
	python -m pytest tests/test_obs.py tests/test_perf_attr.py -q

# the fleet-observability suite (docs/observability.md §Federation /
# §SLOs & burn rates / §Decode timelines): windowed histograms incl.
# rotation-under-concurrent-observe, labeled Prometheus series + the
# collision-safe tenant-label aliases, the federated pool scrape under a
# mid-scrape worker kill, declarative SLO burn rates + the slo_burn
# chaos spec, decode chrome-trace timelines, flight-dump event rings,
# and cluster-side metric federation
test-slo:
	python -m pytest tests/test_slo.py -q

# SLO burn-rate alert-latency drill (docs/observability.md §SLOs & burn
# rates): injects a hard latency violation and measures evaluation
# ticks until the burn gauge crosses the alert threshold; exits
# non-zero when detection takes more than one window — the
# SLO_r*.json artifact source
bench-slo:
	python -m bigdl_tpu.obs.slo --bench

# the Pallas kernel suite (docs/performance.md §Pallas kernels /
# §Kernel autotuning / §Block-sparse FFN): kernel-vs-oracle parity in
# interpret mode, block-sparse matmul + pruning schedule, autotune
# cache determinism + explicit-kwarg precedence, gradient checks
test-kernels:
	python -m pytest tests/test_ops_pallas.py -q

# read-only perf-regression sentinel over the committed bench trajectory
# (docs/performance.md §Regression sentinel).  NOT a watcher: it never
# writes artifacts — chipup.py stays the single evidence writer.
# `make bench-watch` proves the gate on synthetic rows (the CI step);
# `python -m bigdl_tpu.obs.sentinel fresh.json` checks a real capture.
bench-watch:
	python -m bigdl_tpu.obs.sentinel --smoke

# the input-pipeline suite (docs/data.md): streaming stage parallelism,
# ring safety, worker-count determinism, crash propagation, record IO
test-data:
	python -m pytest tests/test_pipeline_stream.py tests/test_records.py \
	  tests/test_native_vision.py -q

# multi-host sharded ingest (docs/data.md §Multi-host ingest): 2-host
# feed parity (no dup/no loss, byte-identical reconstruction), restart-
# mid-epoch determinism across a process-count change, double-buffered
# dispatch overlap, worker autosizing, measured-window stage rates
test-ingest:
	python -m pytest tests/test_ingest_multihost.py -q

# fused multi-step execution (docs/performance.md): K-vs-1 byte-identical
# trajectories (incl. remainder bundles + on/off-grid resume), poisoned-
# bundle rewind, trigger-edge clamping, auto-K, /metrics lines
test-bundle:
	python -m pytest tests/test_step_bundle.py -q

# quantized + overlapped gradient collectives (docs/parallelism.md
# §Gradient compression & bucketed overlap): blockwise-int8 primitives
# vs the f32 oracle, int8-vs-fp32 loss parity on a 2-device CPU mesh,
# bucketed==monolithic trajectories, honest wire-dtype ledger,
# bf16_grads deprecation shim, overlap audit, MULTICHIP sentinel rows
test-collectives:
	python -m pytest tests/test_grad_comm.py -q

# the declarative sharding layer (docs/parallelism.md §Declarative
# layouts): parallelism= combo-string parser errors, layout-table
# completeness for the transformer/seq2seq/two-tower families (a new
# param landing in silent-replicate FAILS), the replicated-params
# audit gauge/flight line, fsdp x tp == dp loss-trajectory parity on
# the 12L transformer, and model-sharded serving through
# InferenceModel/DecodeEngine with zero unexpected recompiles
test-layout:
	python -m pytest tests/test_layout.py -q

bench:
	python bench.py

# dispatch-gap microbench (small-model geometry); --smoke is the CI gate
# that fails when the K=8 host-overhead reduction drops below 3x
bench-dispatch:
	python bench.py --dispatch

dryrun:
	python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

# strong-scaling + loader-throughput artifacts (committed per round)
bench-scaling:
	python bench_scaling.py

# gradient-compression A/B (docs/parallelism.md §Gradient compression):
# analytic wire ledger fp32/bf16/int8 on the MULTICHIP_LARGE geometry +
# measured loss parity and overlap efficiency; exits non-zero when the
# int8 reduction drops below 3x or parity breaks — the
# MULTICHIP_GRADCOMM_r*.json artifact source
bench-gradcomm:
	python bench_scaling.py --grad-comm

# declarative-layout ledger A/B (docs/parallelism.md §Declarative
# layouts): per-axis collective bytes + per-chip param bytes of
# parallelism="dp" vs "fsdp:2,tp:4" on the 12L transformer geometry;
# exits non-zero when the per-chip param-bytes reduction drops below 4x
# or any parameter silently replicates — the MULTICHIP_LAYOUT_r*.json
# artifact source
bench-layout:
	python bench_scaling.py --layout

bench-loader:
	python bench_loader.py

# the recsys serving suite (docs/recsys.md): feature->recall->ranking
# pipeline end-to-end, sharded-vs-unsharded candidate-id parity, the
# closed (batch, k) recall bucket set under a mixed sweep (zero
# unexpected recompiles), predict_inline tenant routing, POST /recommend
# through the HTTP frontend, and the sharded feature-table merge cap
test-recsys:
	python -m pytest tests/test_recsys_pipeline.py \
	  tests/test_friesian_serving.py tests/test_friesian_sharded.py -q

# sustained-load serving bench (docs/serving.md §Continuous batching):
# subprocess server + keep-alive load clients, reports rps/p50/p99/
# occupancy + the zero-recompile mixed-size sweep; --smoke is the CI gate
bench-serving:
	python bench_serving.py

# token-level decode bench (docs/serving.md §Autoregressive decode):
# streaming keep-alive clients over a mixed prompt/output-length
# geometry; continuous vs whole-batch-restart A/B (>= 2x gated);
# the DECODE_r*.json artifact source
bench-decode:
	python bench_serving.py --decode

# quantized decode bench (docs/quantization.md §Serving memory
# hierarchy): int8 KV pages + int8 serving weights vs f32 on the same
# geometry — greedy token parity, >= 1.8x slot capacity at an equal KV
# HBM budget, zero unexpected recompiles; the DECODE_QUANT_r*.json
# artifact source
bench-decode-quant:
	python bench_serving.py --decode --quant

# speculative decode bench (docs/serving.md §Speculative decoding):
# the weight-shared block-sparse draft + single-call verify vs the
# same engine spec-off on the mixed geometry — byte parity, >= 1.5x
# tokens/s/user, zero unexpected recompiles; the DECODE_SPEC_r*.json
# artifact source
bench-spec:
	python bench_serving.py --decode --spec

# disaggregated decode-fleet bench (docs/serving.md §Decode fleet):
# mixed-geometry streaming clients against a 2-worker pool with the
# KV-aware router + prefill/decode split; TTFT p99 gated at >= 2x
# better than the single-host decode bench; the DECODE_POOL_r*.json
# artifact source
bench-fleet:
	python bench_serving.py --fleet

# chaos variant (docs/serving.md §Fleet fault tolerance): same 2-worker
# pool, a decode worker SIGKILLed mid-run at 24 streaming clients; the
# gate is zero failed requests + exact token parity vs the no-fault
# baseline + bounded recovery p99; the DECODE_CHAOS_r*.json source
bench-fleet-chaos:
	python bench_serving.py --fleet --chaos

# recsys + forecast bench (docs/recsys.md §Bench geometry): sharded
# feature engineering -> TwoTower + TCN(parallelism=dp)/Autoformer
# training, then sustained keep-alive POST /recommend load against the
# mesh-sharded (fsdp:2,tp:4) pipeline; gates candidate-id parity, the
# >= 8x per-chip embedding shrink, and zero unexpected recompiles; the
# RECSYS_r*.json artifact source
bench-recsys:
	python bench_recsys.py

# session-long TPU evidence orchestrator (single instance via flock;
# BENCH_attempts.jsonl evidence trail)
watch:
	nohup python chipup.py >> chipup.log 2>&1 &

# every example end-to-end at tiny sizes (the reference's nightly example
# runs, SURVEY.md §5, scaled for CI); fails on the first broken example
examples:
	BIGDL_TPU_EXAMPLES_TINY=1 sh -c '\
	  set -e; \
	  for f in examples/*.py; do \
	    case $$f in */_sim_mesh.py) continue;; esac; \
	    echo "== $$f"; python $$f; \
	  done'
