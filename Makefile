# Test/bench entry points (CI runs these; see .github/workflows/ci.yml)

.PHONY: test test-fast bench dryrun

test:
	python -m pytest tests/ -q

# the quick pre-commit loop: skips the slow multi-process/serving suites
test-fast:
	python -m pytest tests/ -q -x --ignore=tests/test_multiprocess.py \
	  --ignore=tests/test_serving.py

bench:
	python bench.py

dryrun:
	python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

# strong-scaling + loader-throughput artifacts (committed per round)
bench-scaling:
	python bench_scaling.py

bench-loader:
	python bench_loader.py

# session-long TPU availability watcher (BENCH_attempts.jsonl evidence)
watch:
	nohup python bench_watch.py > bench_watch.log 2>&1 &
