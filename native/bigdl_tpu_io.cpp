// bigdl_tpu native host-side IO/vision kernels.
//
// Reference analog: BigDL's native layer — the OpenCV JNI vision pipeline
// (com.intel.analytics.bigdl.opencv, feature/transform/vision) and the
// per-executor ThreadPool that assembles MiniBatches (SURVEY.md §3.2, L0).
// On TPU the device math belongs to XLA/Pallas; what stays native is the
// HOST hot path: image decode-side transforms (resize/crop/flip/normalize)
// and multi-threaded minibatch assembly that must keep up with the chips'
// input bandwidth.  Exposed as a plain C ABI consumed via ctypes
// (no pybind11 in the image).
//
// Build: g++ -O3 -std=c++17 -shared -fPIC -o libbigdl_tpu_io.so bigdl_tpu_io.cpp -lpthread

#include <algorithm>
#include <atomic>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <functional>
#include <mutex>
#include <queue>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

// JPEG decode (the OpenCV-JNI imdecode analog).  Built with -ljpeg when
// libjpeg is present; -DBTIO_NO_JPEG compiles the stubs so every other op
// still loads on boxes without the library (python falls back to PIL).
#ifndef BTIO_NO_JPEG
#include <csetjmp>
#include <jpeglib.h>
#endif

extern "C" {

// ---------------------------------------------------------------------------
// Single-image ops (uint8 HWC in, uint8/float32 HWC out)
// ---------------------------------------------------------------------------

// Bilinear resize, uint8 HWC -> uint8 HWC.
void btio_resize_bilinear_u8(const uint8_t* src, int sh, int sw, int c,
                             uint8_t* dst, int dh, int dw) {
  const float ry = dh > 1 ? (float)(sh - 1) / (dh - 1) : 0.f;
  const float rx = dw > 1 ? (float)(sw - 1) / (dw - 1) : 0.f;
  for (int y = 0; y < dh; ++y) {
    const float fy = y * ry;
    const int y0 = (int)fy;
    const int y1 = std::min(y0 + 1, sh - 1);
    const float wy = fy - y0;
    for (int x = 0; x < dw; ++x) {
      const float fx = x * rx;
      const int x0 = (int)fx;
      const int x1 = std::min(x0 + 1, sw - 1);
      const float wx = fx - x0;
      const uint8_t* p00 = src + (y0 * sw + x0) * c;
      const uint8_t* p01 = src + (y0 * sw + x1) * c;
      const uint8_t* p10 = src + (y1 * sw + x0) * c;
      const uint8_t* p11 = src + (y1 * sw + x1) * c;
      uint8_t* q = dst + (y * dw + x) * c;
      for (int k = 0; k < c; ++k) {
        const float top = p00[k] + (p01[k] - p00[k]) * wx;
        const float bot = p10[k] + (p11[k] - p10[k]) * wx;
        q[k] = (uint8_t)std::lround(top + (bot - top) * wy);
      }
    }
  }
}

// Crop a (ch x cw) window at (oy, ox), uint8 HWC.
void btio_crop_u8(const uint8_t* src, int sh, int sw, int c, int oy, int ox,
                  uint8_t* dst, int ch_, int cw) {
  (void)sh;
  for (int y = 0; y < ch_; ++y) {
    std::memcpy(dst + y * cw * c, src + ((oy + y) * sw + ox) * c,
                (size_t)cw * c);
  }
}

// Horizontal flip in place, uint8 HWC.
void btio_hflip_u8(uint8_t* img, int h, int w, int c) {
  std::vector<uint8_t> tmp(c);
  for (int y = 0; y < h; ++y) {
    uint8_t* row = img + (size_t)y * w * c;
    for (int x = 0; x < w / 2; ++x) {
      uint8_t* a = row + (size_t)x * c;
      uint8_t* b = row + (size_t)(w - 1 - x) * c;
      std::memcpy(tmp.data(), a, c);
      std::memcpy(a, b, c);
      std::memcpy(b, tmp.data(), c);
    }
  }
}

// uint8 HWC -> float32 HWC with per-channel (x/255 - mean) / std.
void btio_normalize_f32(const uint8_t* src, int h, int w, int c,
                        const float* mean, const float* stdv, float* dst) {
  std::vector<float> scale(c), shift(c);
  for (int k = 0; k < c; ++k) {
    const float inv = 1.f / stdv[k];
    scale[k] = inv / 255.f;
    shift[k] = -mean[k] * inv;
  }
  const size_t n = (size_t)h * w;
  for (size_t i = 0; i < n; ++i) {
    const uint8_t* p = src + i * c;
    float* q = dst + i * c;
    for (int k = 0; k < c; ++k) q[k] = p[k] * scale[k] + shift[k];
  }
}

// ---------------------------------------------------------------------------
// Threaded batch pipeline: N worker threads run resize+crop+flip+normalize
// per image straight into its slot of a contiguous NHWC float32 batch.
// (Reference analog: Engine.ThreadPool invokeAndWait over per-core
// transformer chains in SampleToMiniBatch.)
// ---------------------------------------------------------------------------

struct Pipeline {
  std::vector<std::thread> workers;
  std::queue<std::function<void()>> jobs;
  std::mutex mu;
  std::condition_variable cv;
  std::condition_variable done_cv;
  int outstanding = 0;
  bool stop = false;

  explicit Pipeline(int n) {
    for (int i = 0; i < n; ++i) {
      workers.emplace_back([this] {
        for (;;) {
          std::function<void()> job;
          {
            std::unique_lock<std::mutex> lk(mu);
            cv.wait(lk, [this] { return stop || !jobs.empty(); });
            if (stop && jobs.empty()) return;
            job = std::move(jobs.front());
            jobs.pop();
          }
          job();
          {
            std::lock_guard<std::mutex> lk(mu);
            if (--outstanding == 0) done_cv.notify_all();
          }
        }
      });
    }
  }
  ~Pipeline() {
    {
      std::lock_guard<std::mutex> lk(mu);
      stop = true;
    }
    cv.notify_all();
    for (auto& t : workers) t.join();
  }
  void submit(std::function<void()> f) {
    {
      std::lock_guard<std::mutex> lk(mu);
      jobs.push(std::move(f));
      ++outstanding;
    }
    cv.notify_one();
  }
  void wait() {
    std::unique_lock<std::mutex> lk(mu);
    done_cv.wait(lk, [this] { return outstanding == 0; });
  }
};

void* btio_pipeline_create(int num_threads) {
  return new Pipeline(std::max(1, num_threads));
}

void btio_pipeline_destroy(void* p) { delete (Pipeline*)p; }

// One image job: src uint8 HWC (sh, sw, c) -> batch slot i of a float32
// NHWC batch (n, oh, ow, c):  resize to (rh, rw) -> crop (oh, ow) at
// (cy, cx) -> optional hflip -> normalize.
// ---------------------------------------------------------------------------
// JPEG decode
// ---------------------------------------------------------------------------

#ifndef BTIO_NO_JPEG
struct BtioJpegErr {
  jpeg_error_mgr mgr;
  jmp_buf jb;
};

static void btio_jpeg_fail(j_common_ptr cinfo) {
  longjmp(((BtioJpegErr*)cinfo->err)->jb, 1);
}

// Peek the dimensions of an encoded JPEG; returns 0 on success.
int btio_jpeg_dims(const uint8_t* data, int64_t len, int* h, int* w,
                   int* c) {
  jpeg_decompress_struct cinfo;
  BtioJpegErr err;
  cinfo.err = jpeg_std_error(&err.mgr);
  err.mgr.error_exit = btio_jpeg_fail;
  if (setjmp(err.jb)) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, data, (unsigned long)len);
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  *h = (int)cinfo.image_height;
  *w = (int)cinfo.image_width;
  *c = 3;  // decode always lands in RGB
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

// Decode into caller-allocated (h, w, 3) RGB uint8; returns 0 on success.
int btio_jpeg_decode(const uint8_t* data, int64_t len, uint8_t* dst,
                     int h, int w) {
  jpeg_decompress_struct cinfo;
  BtioJpegErr err;
  cinfo.err = jpeg_std_error(&err.mgr);
  err.mgr.error_exit = btio_jpeg_fail;
  if (setjmp(err.jb)) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, data, (unsigned long)len);
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = JCS_RGB;  // grayscale/CMYK sources land as RGB
  jpeg_start_decompress(&cinfo);
  if ((int)cinfo.output_height != h || (int)cinfo.output_width != w ||
      cinfo.output_components != 3) {
    jpeg_abort_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  while (cinfo.output_scanline < cinfo.output_height) {
    JSAMPROW row = dst + (size_t)cinfo.output_scanline * w * 3;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

int btio_jpeg_available() { return 1; }
#else
int btio_jpeg_dims(const uint8_t*, int64_t, int*, int*, int*) { return -1; }
int btio_jpeg_decode(const uint8_t*, int64_t, uint8_t*, int, int) {
  return -1;
}
int btio_jpeg_available() { return 0; }
#endif

struct ImageJob {
  const uint8_t* src;
  int sh, sw, c;
  int rh, rw;       // resize target (0 = skip resize)
  int cy, cx;       // crop offset
  int flip;         // 0/1
  const float* mean;
  const float* stdv;
  float* dst;       // slot pointer (oh*ow*c floats)
  int oh, ow;
};

// The fused pixel loop, parameterized on channel count so the c==3 hot
// case (every vision path) compiles with the inner loop unrolled; the
// always_inline + literal-3 call below makes gcc clone and constant-fold
// it rather than branch on c per channel.  (extern "C++": templates cannot
// take the file's C linkage.)
extern "C++" {
template <int C>
__attribute__((always_inline)) static inline void fused_rows(
    const ImageJob& j, float ry, const float* scale, const float* shift,
    const int32_t* xo0, const int32_t* xo1, const float* xw, int c) {
  for (int y = 0; y < j.oh; ++y) {
    const float fy = (j.cy + y) * ry;
    const int y0 = (int)fy;
    const int y1 = std::min(y0 + 1, j.sh - 1);
    const float wy = fy - y0;
    const uint8_t* row0 = j.src + (size_t)y0 * j.sw * c;
    const uint8_t* row1 = j.src + (size_t)y1 * j.sw * c;
    float* q = j.dst + (size_t)y * j.ow * c;
    for (int x = 0; x < j.ow; ++x) {
      const float wx = xw[x];
      const uint8_t* p00 = row0 + xo0[x];
      const uint8_t* p01 = row0 + xo1[x];
      const uint8_t* p10 = row1 + xo0[x];
      const uint8_t* p11 = row1 + xo1[x];
      float* o = q + (size_t)x * c;
      const int kc = C > 0 ? C : c;
      for (int k = 0; k < kc; ++k) {
        const float top = p00[k] + (p01[k] - p00[k]) * wx;
        const float bot = p10[k] + (p11[k] - p10[k]) * wx;
        const uint8_t v = (uint8_t)std::lround(top + (bot - top) * wy);
        o[k] = v * scale[k] + shift[k];
      }
    }
  }
}

static void fused_pass(const ImageJob& j, float ry, const float* scale,
                       const float* shift, const int32_t* xo0,
                       const int32_t* xo1, const float* xw) {
  if (j.c == 3) {
    fused_rows<3>(j, ry, scale, shift, xo0, xo1, xw, 3);
  } else {
    fused_rows<0>(j, ry, scale, shift, xo0, xo1, xw, j.c);
  }
}
}  // extern "C++"

// Fused single pass: for each OUTPUT pixel, bilinear-sample the source at
// the position the staged resize->crop->flip chain would have read, round
// through uint8 (so results stay byte-identical to the staged ops the
// fallbacks and parity tests compute), and write the normalized float32
// straight into the batch slot.  Versus the staged path this computes only
// the crop window's share of the resize (a 224-crop of a 256-resize skips
// 23% of the samples), elides the crop memcpy, the flip copy+swap, and the
// separate normalize read/write, and allocates no intermediate buffers —
// the per-image cost that made decode+augment the pipeline's slow stage.
static void run_image_job(const ImageJob j) {
  const int rh = (j.rh > 0) ? j.rh : j.sh;  // dims entering the crop stage
  const int rw = (j.rh > 0) ? j.rw : j.sw;
  const float ry = rh > 1 ? (float)(j.sh - 1) / (rh - 1) : 0.f;
  const float rx = rw > 1 ? (float)(j.sw - 1) / (rw - 1) : 0.f;
  std::vector<float> scale(j.c), shift(j.c);
  for (int k = 0; k < j.c; ++k) {
    const float inv = 1.f / j.stdv[k];
    scale[k] = inv / 255.f;
    shift[k] = -j.mean[k] * inv;
  }
  // per-column sample table (source offsets + weight), computed once per
  // image instead of once per pixel; flip runs AFTER crop in the staged
  // chain, so output column x reads resized column cx + (ow-1-x)
  std::vector<int32_t> xo0(j.ow), xo1(j.ow);
  std::vector<float> xw(j.ow);
  for (int x = 0; x < j.ow; ++x) {
    const int sx = j.cx + (j.flip ? (j.ow - 1 - x) : x);
    const float fx = sx * rx;
    const int x0 = (int)fx;
    xo0[x] = x0 * j.c;
    xo1[x] = std::min(x0 + 1, j.sw - 1) * j.c;
    xw[x] = fx - x0;
  }
  fused_pass(j, ry, scale.data(), shift.data(), xo0.data(), xo1.data(),
             xw.data());
}

// Submit a whole batch of image jobs described by parallel arrays, then wait.
// srcs: n pointers; dims: n*(sh,sw); geom: n*(rh,rw,cy,cx,flip);
// dst: contiguous (n, oh, ow, c) float32.
void btio_process_batch(void* pipe, int n, const uint8_t** srcs,
                        const int* dims, const int* geom, int c, int oh,
                        int ow, const float* mean, const float* stdv,
                        float* dst) {
  Pipeline* p = (Pipeline*)pipe;
  const size_t slot = (size_t)oh * ow * c;
  for (int i = 0; i < n; ++i) {
    ImageJob j;
    j.src = srcs[i];
    j.sh = dims[2 * i];
    j.sw = dims[2 * i + 1];
    j.c = c;
    j.rh = geom[5 * i];
    j.rw = geom[5 * i + 1];
    j.cy = geom[5 * i + 2];
    j.cx = geom[5 * i + 3];
    j.flip = geom[5 * i + 4];
    j.mean = mean;
    j.stdv = stdv;
    j.dst = dst + slot * i;
    j.oh = oh;
    j.ow = ow;
    p->submit([j] { run_image_job(j); });
  }
  p->wait();
}

// Decode+transform batch: srcs are ENCODED JPEG buffers (lens[i] bytes
// each); each worker decodes to RGB then runs the same resize/crop/flip/
// normalize job.  geom as in btio_process_batch.  Per-image status lands
// in status[i] (0 ok, -1 decode failure; that slot's dst is untouched).
void btio_decode_batch(void* pipe, int n, const uint8_t** srcs,
                       const int64_t* lens, const int* geom, int oh, int ow,
                       const float* mean, const float* stdv, float* dst,
                       int* status) {
  Pipeline* p = (Pipeline*)pipe;
  const size_t slot = (size_t)oh * ow * 3;
  for (int i = 0; i < n; ++i) {
    const uint8_t* src = srcs[i];
    int64_t len = lens[i];
    const int* g = geom + 5 * i;
    float* out = dst + slot * i;
    int* st = status + i;
    p->submit([src, len, g, oh, ow, mean, stdv, out, st] {
      int h, w, c;
      if (btio_jpeg_dims(src, len, &h, &w, &c) != 0) {
        *st = -1;
        return;
      }
      std::vector<uint8_t> pix((size_t)h * w * 3);
      if (btio_jpeg_decode(src, len, pix.data(), h, w) != 0) {
        *st = -1;
        return;
      }
      // bounds-check the crop against the post-resize dims — the caller
      // could not know them before decode, and run_image_job's crop
      // would read out of bounds on a violation
      const int eh = g[0] > 0 ? g[0] : h;
      const int ew = g[0] > 0 ? g[1] : w;
      if (g[2] < 0 || g[3] < 0 || g[2] + oh > eh || g[3] + ow > ew) {
        *st = -2;
        return;
      }
      ImageJob j;
      j.src = pix.data();
      j.sh = h;
      j.sw = w;
      j.c = 3;
      j.rh = g[0];
      j.rw = g[1];
      j.cy = g[2];
      j.cx = g[3];
      j.flip = g[4];
      j.mean = mean;
      j.stdv = stdv;
      j.dst = out;
      j.oh = oh;
      j.ow = ow;
      run_image_job(j);
      *st = 0;
    });
  }
  p->wait();
}

// ---------------------------------------------------------------------------
// Gather-assemble: copy rows[idx] of a (num, row_elems) float32 array into a
// contiguous batch — the SampleToMiniBatch copy, parallelized.
// ---------------------------------------------------------------------------
void btio_gather_rows_f32(void* pipe, const float* src, const int64_t* idx,
                          int n, int64_t row_elems, float* dst) {
  Pipeline* p = (Pipeline*)pipe;
  const int chunk = std::max(1, n / (int)(((Pipeline*)pipe)->workers.size() * 4));
  for (int s = 0; s < n; s += chunk) {
    const int e = std::min(n, s + chunk);
    p->submit([=] {
      for (int i = s; i < e; ++i) {
        std::memcpy(dst + (size_t)i * row_elems,
                    src + (size_t)idx[i] * row_elems,
                    sizeof(float) * row_elems);
      }
    });
  }
  p->wait();
}

// ---------------------------------------------------------------------------
// Record file reader: fixed-size records, memory-mapped — the native
// data-loader executor (the RDD-partition file-read analog).  Layout:
//   bytes 0..7   magic "BTRECv1\0"
//   bytes 8..15  uint64 record_bytes
//   bytes 16..23 uint64 n_records
//   bytes 24..   records, contiguous
// ---------------------------------------------------------------------------

struct RecordFile {
  int fd = -1;
  uint8_t* map = nullptr;
  size_t map_len = 0;
  uint64_t record_bytes = 0;
  uint64_t n_records = 0;
};

void* btio_records_open(const char* path) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || (size_t)st.st_size < 24) {
    ::close(fd);
    return nullptr;
  }
  void* m = mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  if (m == MAP_FAILED) {
    ::close(fd);
    return nullptr;
  }
  uint8_t* b = (uint8_t*)m;
  if (std::memcmp(b, "BTRECv1\0", 8) != 0) {
    munmap(m, st.st_size);
    ::close(fd);
    return nullptr;
  }
  RecordFile* rf = new RecordFile();
  rf->fd = fd;
  rf->map = b;
  rf->map_len = st.st_size;
  std::memcpy(&rf->record_bytes, b + 8, 8);
  std::memcpy(&rf->n_records, b + 16, 8);
  // Overflow-safe bounds check: record_bytes * n_records can wrap uint64 for
  // a corrupt/hostile header, so divide instead of multiplying.
  if (rf->record_bytes == 0 ||
      rf->n_records > (rf->map_len - 24) / rf->record_bytes) {
    munmap(m, st.st_size);
    ::close(fd);
    delete rf;
    return nullptr;
  }
  return rf;
}

int64_t btio_records_count(void* h) {
  return h ? (int64_t)((RecordFile*)h)->n_records : -1;
}

int64_t btio_records_bytes(void* h) {
  return h ? (int64_t)((RecordFile*)h)->record_bytes : -1;
}

// Gather records[idx[0..n)] into out (n, record_bytes), fanned out over the
// pipeline's worker threads (memcpy from the mapped region; the page cache
// is the shared buffer pool).
void btio_records_gather(void* h, void* pipe, const int64_t* idx, int n,
                         uint8_t* out) {
  RecordFile* rf = (RecordFile*)h;
  const uint8_t* base = rf->map + 24;
  const size_t rb = rf->record_bytes;
  Pipeline* p = (Pipeline*)pipe;
  if (p == nullptr) {
    for (int i = 0; i < n; ++i)
      std::memcpy(out + (size_t)i * rb, base + (size_t)idx[i] * rb, rb);
    return;
  }
  const int chunk = std::max(1, n / (int)(p->workers.size() * 4));
  for (int s = 0; s < n; s += chunk) {
    const int e = std::min(n, s + chunk);
    p->submit([=] {
      for (int i = s; i < e; ++i)
        std::memcpy(out + (size_t)i * rb, base + (size_t)idx[i] * rb, rb);
    });
  }
  p->wait();
}

void btio_records_close(void* h) {
  RecordFile* rf = (RecordFile*)h;
  if (!rf) return;
  if (rf->map) munmap(rf->map, rf->map_len);
  if (rf->fd >= 0) ::close(rf->fd);
  delete rf;
}

int btio_version() { return 4; }

}  // extern "C"
