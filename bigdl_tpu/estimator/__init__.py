"""Estimator — the Orca-equivalent scaling API (SURVEY.md §7 step 7).

Reference analog (unverified — mount empty): ``python/orca/src/bigdl/orca/``
— ``init_orca_context`` / ``Estimator.from_torch(model_creator, ...)`` with
pluggable backends over Spark/Ray.  TPU-native: the single backend is
``jax_tpu`` — one controller process per TPU-VM host, rendezvous via
``jax.distributed.initialize`` (replacing Spark barrier stages + gloo/NCCL),
training through the ZeRO-1 sharded train step over the mesh.
"""

from bigdl_tpu.estimator.estimator import Estimator, init_context, stop_context

# reference spellings: orca.common.init_orca_context/stop_orca_context and
# the dllib entry init_nncontext (returns the engine, the SparkContext role)
init_orca_context = init_context
stop_orca_context = stop_context
init_nncontext = init_context

__all__ = ["Estimator", "init_context", "stop_context",
           "init_orca_context", "stop_orca_context", "init_nncontext"]
