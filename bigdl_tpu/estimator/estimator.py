"""Orca-style Estimator on the jax_tpu backend.

Reference call stack being replaced (SURVEY.md §4.3, unverified):
``Estimator.from_torch(backend="spark").fit`` → Spark barrier stage → one DDP
rank per executor → gloo ring allreduce.  Here: creators are plain callables
evaluated in-process (multi-controller — every TPU-VM host runs this same
program), data shards map to the host's slice of the global batch, and
gradient sync is the XLA collective inside the jitted train step.
"""

import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import numpy as np

import jax

from bigdl_tpu.data.dataset import DataSet
from bigdl_tpu.data.shards import XShards
from bigdl_tpu.optim.optim_method import OptimMethod
from bigdl_tpu.optim.optimizer import Optimizer, TrainedModel
from bigdl_tpu.optim.trigger import Trigger
from bigdl_tpu.optim.validation import StatsAccumulator, ValidationMethod
from bigdl_tpu.runtime.engine import Engine, EngineConfig, init_engine
from bigdl_tpu.utils.log import get_logger

log = get_logger("bigdl_tpu.estimator")


def init_context(cluster_mode: str = "local",
                 coordinator_address: Optional[str] = None,
                 num_processes: Optional[int] = None,
                 process_id: Optional[int] = None,
                 **mesh_axes) -> Engine:
    """``init_orca_context`` analog.

    - ``cluster_mode="local"``: single process, all local devices.
    - ``cluster_mode="multihost"``: one process per TPU-VM host;
      pass coordinator_address/num_processes/process_id (or set
      BIGDL_TPU_COORDINATOR/... env vars) — the
      ``jax.distributed.initialize`` rendezvous replaces Spark's
      barrier-stage + gloo bootstrap (reference stack §4.3).
    """
    cfg = EngineConfig.from_env()
    if cluster_mode == "multihost":
        if coordinator_address is not None:
            cfg.coordinator_address = coordinator_address
            cfg.num_processes = num_processes
            cfg.process_id = process_id
        if cfg.coordinator_address is None:
            raise ValueError(
                "multihost mode needs coordinator_address (or "
                "BIGDL_TPU_COORDINATOR env)")
    elif cluster_mode != "local":
        raise ValueError(f"unknown cluster_mode {cluster_mode!r}; "
                         "use 'local' or 'multihost'")
    return init_engine(cfg, **mesh_axes)


def stop_context() -> None:
    Engine.reset()


def _to_xy(data, batch_size, shuffle=True):
    """Normalize fit/evaluate inputs to (x, y) numpy arrays.

    Accepts: (x, y) tuple, dict {"x":, "y":}, XShards of either, a DataSet,
    or a creator fn (config -> any of the above)."""
    if isinstance(data, DataSet):
        return data
    if callable(data) and not isinstance(data, (tuple, dict, XShards)):
        data = data()
    if isinstance(data, XShards):
        data = data.owned_concat() if jax.process_count() > 1 else data.concat()
    if isinstance(data, dict):
        data = (data["x"], data["y"])
    if isinstance(data, (tuple, list)):
        x, y = data
        return DataSet.array(np.asarray(x), np.asarray(y))
    raise TypeError(f"unsupported data type {type(data)}")


class Estimator:
    """``Estimator.from_module(...)`` — fit/evaluate/predict driver."""

    def __init__(self, model_creator: Callable[[Dict], Any],
                 optimizer_creator: Callable[[Dict], OptimMethod],
                 loss_creator: Callable[[Dict], Any],
                 config: Optional[Dict] = None,
                 backend: str = "jax_tpu"):
        if backend != "jax_tpu":
            raise ValueError(
                f"backend {backend!r} not supported; the TPU rebuild has one "
                "native backend: 'jax_tpu' (reference backends bigdl/ray/"
                "horovod/spark all reduce to sync data-parallel — §3.5)")
        self.config = dict(config or {})
        self.model = model_creator(self.config)
        self.optim_method = optimizer_creator(self.config)
        self.criterion = loss_creator(self.config)
        self._trained: Optional[TrainedModel] = None
        self._loaded_variables: Optional[Dict[str, Any]] = None
        self._last_stats: Dict[str, Any] = {}

    # -- constructors (reference: from_torch / from_keras) ------------------
    @staticmethod
    def from_module(model_creator, optimizer_creator, loss_creator,
                    config=None, backend="jax_tpu") -> "Estimator":
        return Estimator(model_creator, optimizer_creator, loss_creator,
                         config, backend)

    @staticmethod
    def from_torch(model_creator, optimizer_creator, loss_creator,
                   config=None, backend="jax_tpu",
                   example_input=None) -> "Estimator":
        """Train a STOCK ``torch.nn.Module`` on the mesh — the reference's
        headline Orca capability (``Estimator.from_torch``, SURVEY.md §4.3).

        - ``model_creator(config) -> torch.nn.Module``: converted once via
          ``utils.torch_convert`` (fx graph → keras-engine Model, NHWC;
          weights carried over) — torch never runs on the hot path.
        - ``optimizer_creator``: ``(model, config)`` returning a
          ``torch.optim.Optimizer`` (hyperparameters mapped to the native
          OptimMethod) or ``(config)`` returning an OptimMethod.
        - ``loss_creator(config)``: a torch loss (mapped) or a criterion.
        - ``example_input``: numpy array in TORCH layout (NCHW for conv
          nets) for shape propagation.  NOTE: after conversion the model
          consumes channels-LAST inputs.

        ``get_model()`` returns the trained variables; ``state_dict()``
        exports them back into torch tensors keyed like the original
        module (via ``utils.interop.to_torch``)."""
        if backend != "jax_tpu":
            raise ValueError(f"backend {backend!r} not supported")
        from bigdl_tpu.utils.torch_convert import (convert_torch_loss,
                                                   convert_torch_optimizer,
                                                   from_torch_module)

        import inspect

        cfg = dict(config or {})
        tmodel = model_creator(cfg)
        model, variables = from_torch_module(tmodel, example_input)
        n_args = len(inspect.signature(optimizer_creator).parameters)
        topt = (optimizer_creator(tmodel, cfg) if n_args >= 2
                else optimizer_creator(cfg))
        est = Estimator.__new__(Estimator)
        est.config = cfg
        est.model = model
        est.optim_method = convert_torch_optimizer(topt)
        est.criterion = convert_torch_loss(loss_creator(cfg))
        est._trained = None
        est._loaded_variables = variables   # predict/evaluate pre-finetune
        est._initial_variables = variables
        est._torch_model = tmodel
        est._last_stats = {}
        return est

    @staticmethod
    def from_keras(model_creator, config=None, backend="jax_tpu") -> "Estimator":
        """Train a keras model on the mesh — BOTH kinds (reference
        ``orca/learn/tf2/estimator.py``: ``Estimator.from_keras`` trains
        stock ``tf.keras`` models):

        - a COMPILED model built with THIS package's keras API
          (``bigdl_tpu.keras``), or
        - a COMPILED **stock tf.keras model** (Keras 3): converted once via
          ``utils.keras_convert`` (layer graph walked, weights carried
          over, optimizer/loss mapped to native equivalents) — TF never
          runs on the hot path.  After ``fit``, ``export_to_keras()``
          writes the trained weights back into the original keras model.
        """
        cfg = dict(config or {})
        model = model_creator(cfg)
        if type(model).__module__.split(".")[0] in ("keras", "tf_keras") \
                or "tensorflow" in type(model).__module__:
            from bigdl_tpu.utils.keras_convert import (
                convert_keras_loss, convert_keras_optimizer, from_tf_keras)

            kmodel = model
            if getattr(kmodel, "optimizer", None) is None or \
                    getattr(kmodel, "loss", None) is None:
                raise ValueError(
                    "from_keras: compile() the tf.keras model first "
                    "(optimizer + loss are mapped to native equivalents)")
            native, variables = from_tf_keras(kmodel)
            est = Estimator.__new__(Estimator)
            est.config = cfg
            est.model = native
            est.optim_method = convert_keras_optimizer(kmodel.optimizer)
            est.criterion = convert_keras_loss(kmodel.loss)
            est._trained = None
            est._loaded_variables = variables  # predict/evaluate pre-finetune
            est._initial_variables = variables
            est._tf_keras_model = kmodel
            est._last_stats = {}
            return est
        compiled = getattr(model, "_compiled", None)
        if compiled is None:
            raise ValueError("from_keras: creator must return a compiled model")
        est = Estimator.__new__(Estimator)
        est.config = cfg
        est.model = model
        est.optim_method = compiled["optimizer"]
        est.criterion = compiled["loss"]
        est._trained = None
        est._loaded_variables = None
        est._last_stats = {}
        return est

    def export_to_keras(self):
        """For stock-tf.keras estimators: write the trained weights back
        into the ORIGINAL keras model (in place) and return it."""
        km = getattr(self, "_tf_keras_model", None)
        if km is None:
            raise RuntimeError("not a stock-tf.keras estimator")
        from bigdl_tpu.utils.keras_convert import export_tf_keras_weights

        export_tf_keras_weights(self.model, self.get_model(), km)
        return km

    # -- training -----------------------------------------------------------
    def fit(self, data, epochs: int = 1, batch_size: int = 32,
            validation_data=None,
            validation_methods: Sequence[ValidationMethod] = (),
            checkpoint_path: Optional[str] = None,
            checkpoint_trigger: Optional[Trigger] = None,
            fault_tolerance=False,
            profile_dir: Optional[str] = None) -> Dict[str, Any]:
        """``config["parallelism"]`` (or ``EngineConfig.parallelism`` /
        ``BIGDL_TPU_PARALLELISM``): a declarative combo string —
        ``"dp" | "fsdp" | "tp:8" | "dp:4,tp:2"`` — resolved against the
        live device set into a named (data, fsdp, tp, seq) mesh and a
        per-model :class:`~bigdl_tpu.parallel.SpecLayout` table; the fit
        then runs GSPMD end to end (``jax.jit`` + ``NamedSharding``, XLA
        inserts the collectives), so fsdp x tp trains models whose
        parameters do not fit one chip with NO model-code change
        (docs/parallelism.md §Declarative layouts).  Unset keeps the
        classic ZeRO-1 driver with its full checkpoint/fault-tolerance
        integration.

        ``fault_tolerance``: opt-in recovery for the whole fit — True
        runs the training loop under a ``resilience.Supervisor`` with the
        engine's FailurePolicy (pass a ``FailurePolicy`` to override):
        failures that escape the driver's in-run retry are classified,
        backed off per cause, and training re-enters from the newest
        shard-complete checkpoint (``checkpoint_path`` strongly advised —
        without one the supervisor can only restart from scratch).

        ``profile_dir``: capture a jax.profiler trace over a warm window
        of iterations into this directory (``EngineConfig.profile_dir``
        sets it fleet-wide); the profiler is closed when the fit ends,
        even mid-window."""
        ds = _to_xy(data, batch_size)
        par = self.config.get("parallelism")
        if par is None:
            par = getattr(Engine.get().config, "parallelism", None)
        if par is not None:
            # features the layout path does not carry yet must fail
            # LOUDLY — a fleet-wide BIGDL_TPU_PARALLELISM must never
            # silently drop a job's explicitly requested resilience
            unsupported = [n for n, v in (
                ("fault_tolerance", fault_tolerance),
                ("checkpoint_trigger", checkpoint_trigger),
                ("profile_dir", profile_dir)) if v]
            if unsupported:
                raise ValueError(
                    f"parallelism={par!r} (declarative GSPMD fit) does "
                    f"not support {', '.join(unsupported)} yet — drop "
                    "them or unset parallelism to use the classic "
                    "ZeRO-1 driver (docs/parallelism.md §Declarative "
                    "layouts)")
            return self._fit_layout(ds, str(par), epochs, batch_size,
                                    validation_data, validation_methods,
                                    checkpoint_path)
        opt = Optimizer(self.model, ds, self.criterion,
                        batch_size=batch_size)
        # input-pipeline knobs ride the creator config (docs/data.md):
        # host_prefetch (producer lookahead; 0 = inline) and streaming
        # (stage-parallel batch path for datasets that support it)
        if "host_prefetch" in self.config:
            opt.host_prefetch = int(self.config["host_prefetch"])
        if "streaming" in self.config:
            opt.streaming = bool(self.config["streaming"])
        if "steps_per_call" in self.config:
            # fused multi-step execution (docs/performance.md): K train
            # steps per XLA program, or "auto" to size K from measured
            # dispatch-vs-step time
            spc = self.config["steps_per_call"]
            opt.steps_per_call = spc if spc == "auto" else int(spc)
        if "grad_comm" in self.config:
            # gradient-sync wire format (docs/parallelism.md §Gradient
            # compression): "fp32" | "bf16" | "int8"
            opt.grad_comm = str(self.config["grad_comm"])
        if "comm_bucket_bytes" in self.config:
            # bucketed gradient sync: max flat-gradient bytes per
            # collective, so communication overlaps neighbouring compute
            opt.comm_bucket_bytes = int(self.config["comm_bucket_bytes"])
        slo_ev = None
        if "slo_specs" in self.config:
            # declarative SLOs over the fit (docs/observability.md §SLOs
            # & burn rates): burn-rate gauges + slo_burn flight events
            # for the run's objectives; stopped when the fit ends.  A
            # bad spec degrades observability, never training
            from bigdl_tpu.obs.slo import SLOEvaluator

            try:
                slo_ev = SLOEvaluator(self.config["slo_specs"]).start()
            except Exception as e:  # noqa: BLE001
                log.error("slo_specs unusable (%s); SLO evaluation "
                          "disabled for this fit", e)
        if profile_dir is not None:
            opt.set_profile(profile_dir)
        if getattr(self, "_initial_variables", None) is not None:
            opt.set_initial_variables(self._initial_variables)
        opt.set_optim_method(self.optim_method)
        opt.set_end_when(Trigger.max_epoch(epochs))
        if validation_data is not None:
            vds = _to_xy(validation_data, batch_size)
            methods = list(validation_methods) or None
            if methods is None:
                from bigdl_tpu.optim.validation import Loss

                methods = [Loss(self.criterion)]
            opt.set_validation(Trigger.every_epoch(), vds, methods)
        if checkpoint_path is not None:
            opt.set_checkpoint(checkpoint_path,
                               checkpoint_trigger or Trigger.every_epoch())
        t0 = time.time()
        try:
            if fault_tolerance:
                from bigdl_tpu.resilience.retry import FailurePolicy
                from bigdl_tpu.resilience.supervisor import Supervisor

                policy = (fault_tolerance
                          if isinstance(fault_tolerance, FailurePolicy)
                          else None)
                if checkpoint_path is None:
                    log.warning("fit(fault_tolerance=...) without "
                                "checkpoint_path: recovery can only "
                                "restart from scratch")
                self._trained = Supervisor(opt, policy=policy).run()
            else:
                self._trained = opt.optimize()
        finally:
            if slo_ev is not None:
                slo_ev.stop()
        self._last_stats = {
            "train_time_s": time.time() - t0,
            "epochs": epochs,
            "num_samples": ds.size(),
        }
        recov = opt.metrics.counter("recoveries_total")
        if recov:
            self._last_stats["recoveries_total"] = recov
            self._last_stats["time_lost_to_recovery_s"] = \
                opt.metrics.counter("time_lost_to_recovery_s")
        return self._last_stats

    def _fit_layout(self, ds, parallelism: str, epochs: int,
                    batch_size: int, validation_data,
                    validation_methods,
                    checkpoint_path: Optional[str]) -> Dict[str, Any]:
        """The declarative GSPMD fit: resolve the ``parallelism=`` combo
        string into a mesh + layout and drive
        :func:`~bigdl_tpu.parallel.fit_layout`.  Same seed + same policy
        grammar => identical data order across policies, so "dp" and
        "fsdp:2,tp:2" trajectories are comparable step for step."""
        from bigdl_tpu.parallel.gspmd import fit_layout

        self._trained, stats = fit_layout(
            self.model, self.criterion, self.optim_method, ds,
            parallelism=parallelism, batch_size=batch_size,
            epochs=epochs, seed=int(self.config.get("seed", 42)),
            log_every=int(self.config.get("log_every", 10)))
        if checkpoint_path is not None:
            # layout fits save the final weights in the durable model
            # format (the periodic-trigger checkpointing stays a classic-
            # driver capability for now — docs/parallelism.md)
            self.save(checkpoint_path)
        if validation_data is not None:
            vds = _to_xy(validation_data, batch_size, shuffle=False)
            methods = list(validation_methods)
            if not methods:
                from bigdl_tpu.optim.validation import Loss

                methods = [Loss(self.criterion)]
            res = self._trained.evaluate(vds, methods, batch_size)
            stats["validation"] = {r.name: r.result for r in res}
        losses = stats.pop("losses", None) or []
        if losses:
            stats["first_loss"] = losses[0]
            stats["final_loss"] = losses[-1]
        self._last_stats = stats
        return stats

    # -- inference ----------------------------------------------------------
    def _loaded_forward(self):
        """Jitted forward over loaded variables (no train-step engine).
        Handles the multi-input pack convention like the trained path."""
        fwd = self.__dict__.get("_loaded_fwd")
        if fwd is None:
            from bigdl_tpu.optim.train_step import as_inputs

            model = self.model

            @jax.jit
            def fwd(params, state, xb):
                out, _ = model.forward(params, state, *as_inputs(xb),
                                       training=False)
                return out

            self._loaded_fwd = fwd
        return fwd

    def _predict_array(self, x, batch_size: int):
        if self._trained is not None:
            return self._trained.predict(x, batch_size)
        # loaded-weights path: plain jitted forward, no train-step engine
        if self._loaded_variables is None:
            raise RuntimeError("call fit() or load() first")
        from bigdl_tpu.optim.train_step import as_inputs

        fwd = self._loaded_forward()
        v = self._loaded_variables
        xs = as_inputs(x)
        n = len(xs[0])
        outs = []
        step = batch_size if batch_size > 0 else n
        for i in range(0, n, step):
            xb = tuple(np.asarray(a[i:i + step]) for a in xs)
            outs.append(np.asarray(
                fwd(v.get("params", {}), v.get("state", {}),
                    xb if len(xb) > 1 else xb[0])))
        return np.concatenate(outs, 0)

    def predict(self, data, batch_size: int = 0):
        if isinstance(data, XShards):
            return data.transform_shard(
                lambda s: self._predict_array(
                    np.asarray(s if not isinstance(s, dict) else s["x"]),
                    batch_size))
        if isinstance(data, (tuple, list)) and all(
                isinstance(a, np.ndarray) or hasattr(a, "shape")
                for a in data):  # multi-input pack (keras-style list too)
            return self._predict_array(
                tuple(np.asarray(a) for a in data), batch_size)
        return self._predict_array(np.asarray(data), batch_size)

    def evaluate(self, data, methods: Sequence[ValidationMethod],
                 batch_size: int = 32) -> Dict[str, float]:
        ds = _to_xy(data, batch_size, shuffle=False)
        if self._trained is not None:
            res = self._trained.evaluate(ds, list(methods), batch_size)
            return {r.name: r.result for r in res}
        # loaded-weights path: host accumulation over the jitted forward
        if self._loaded_variables is None:
            raise RuntimeError("call fit() or load() first")
        from bigdl_tpu.optim.train_step import as_inputs

        fwd = self._loaded_forward()
        v = self._loaded_variables
        methods = list(methods)
        # every process walks ALL batches (params are replicated, there is
        # no cross-process psum on this path — sharding the data here
        # would silently give per-host partial metrics).
        acc = StatsAccumulator()
        for mb in ds.batches(batch_size, shuffle=False, drop_last=False):
            x = mb["input"]
            n_rows = as_inputs(x)[0].shape[0]
            w = mb.get("weight")
            if w is None:
                w = np.ones((n_rows,), np.float32)
            out = fwd(v.get("params", {}), v.get("state", {}), x)
            acc.add([m.batch_stats(out, np.asarray(mb["target"]), w)
                     for m in methods])
        totals = acc.fetch() or [(0.0, 0.0)] * len(methods)
        res = [m.fold(s, c) for m, (s, c) in zip(methods, totals)]
        return {r.name: r.result for r in res}

    def state_dict(self):
        """For ``from_torch`` estimators: trained weights exported back as
        a torch ``state_dict`` (keys match the original torch module)."""
        from bigdl_tpu.utils.torch_convert import export_state_dict

        return export_state_dict(self.model, self.get_model())

    # -- model access (reference: get_model / save / load) ------------------
    def get_model(self):
        if self._trained is not None:
            return self._trained.variables
        if self._loaded_variables is not None:
            return self._loaded_variables
        raise RuntimeError("call fit() or load() first")

    def save(self, path: str) -> None:
        from bigdl_tpu.utils.serializer import save_model

        save_model(path, self.model, self.get_model())

    def load(self, path: str) -> None:
        """Load weights saved by ``save`` — enables predict/evaluate without
        a prior fit (reference: ``Estimator.load`` / ``Module.loadModule``)."""
        from bigdl_tpu.utils.serializer import load_model

        self._loaded_variables = load_model(path)
        self._trained = None

    def _require_fit(self):
        if self._trained is None:
            raise RuntimeError("call fit() first")
