from bigdl_tpu.runtime.engine import Engine, EngineConfig, init_engine
from bigdl_tpu.runtime.mesh import MeshSpec, build_mesh

__all__ = ["Engine", "EngineConfig", "init_engine", "MeshSpec", "build_mesh"]
