"""Device-mesh construction over ICI/DCN.

Replaces the reference's Spark executor-topology inference
(dllib/utils/Engine.scala, unverified — mount empty): where BigDL asks SparkConf
for node/core counts and hard-fails if it cannot infer them, the TPU runtime
introspects ``jax.devices()`` and lays the requested logical axes
(data / model / seq / expert / pipe) out over the physical slice so that the
heavy-traffic axes ride ICI, not DCN.
"""

from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

# Canonical logical axis names, inner-to-outer traffic intensity.  "data" is
# the WITHIN-SLICE allreduce axis (the AllReduceParameter analog);
# model/seq/expert are the tensor/sequence/expert-parallel axes; pipe is
# pipeline stages; "dcn_data" is the cross-slice (DCN) data axis of a
# multislice job — collectives over it are hierarchical: reduce-scatter
# rides ICI first, only 1/ici_data of the gradient crosses DCN.
AXIS_DATA = "data"
AXIS_MODEL = "model"
AXIS_SEQ = "seq"
AXIS_EXPERT = "expert"
AXIS_PIPE = "pipe"
AXIS_DCN = "dcn_data"


try:
    from jax import shard_map as _shard_map  # jax >= 0.6
except ImportError:  # pragma: no cover — older jax
    from jax.experimental.shard_map import shard_map as _shard_map

# the replication-check kwarg was renamed check_rep -> check_vma across jax
# versions; resolve the spelling once so call sites stay version-agnostic
import inspect as _inspect

_CHECK_KW = next((k for k in ("check_vma", "check_rep")
                  if k in _inspect.signature(_shard_map).parameters), None)


def shard_map(f, mesh, in_specs, out_specs, check: bool = False):
    """Version-compat ``shard_map`` with the replication check off by
    default (every caller here disables it: the train step's donated
    buffers and psum_scatter/all_gather pattern trip false positives)."""
    kw = {_CHECK_KW: check} if _CHECK_KW else {}
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kw)


def axis_size(name: str) -> int:
    """Version-compat ``jax.lax.axis_size``: older jax spells it
    ``psum(1, axis)`` (constant-folds to the concrete size; raises
    NameError for an unbound axis, same as the modern call)."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(name)
    return jax.lax.psum(1, name)


def detect_slice_count(devices: Sequence) -> int:
    """Number of distinct TPU slices among ``devices`` (1 when the runtime
    exposes no slice topology — CPU sim, single slice)."""
    ids = set()
    for d in devices:
        s = getattr(d, "slice_index", None)
        if s is None:
            return 1
        ids.add(s)
    return max(1, len(ids))


@dataclass(frozen=True)
class MeshSpec:
    """Logical mesh shape.  Any axis set to 1 is still present (size-1 axes
    are free in XLA) so train steps can be written once against all six
    axes (dcn_data, data, model, seq, expert, pipe).

    ``dcn_data``: cross-slice data-parallel degree.  ``0`` (default)
    auto-detects the slice count from the device topology — a multislice
    job hierarchically splits its data axis without config changes;
    single-slice and CPU-sim runs resolve to 1."""

    data: int = -1  # -1: fill with remaining devices
    model: int = 1
    seq: int = 1
    expert: int = 1
    pipe: int = 1
    dcn_data: int = 0  # 0: auto-detect slice count

    def resolve(self, n_devices: int, n_slices: int = 1) -> Dict[str, int]:
        dcn = self.dcn_data if self.dcn_data > 0 else n_slices
        fixed = {
            AXIS_MODEL: self.model,
            AXIS_SEQ: self.seq,
            AXIS_EXPERT: self.expert,
            AXIS_PIPE: self.pipe,
        }
        prod = int(np.prod(list(fixed.values()))) * dcn
        if self.data == -1:
            if n_devices % prod != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by "
                    f"dcn_data*model*seq*expert*pipe={prod}")
            data = n_devices // prod
        else:
            data = self.data
            if data * prod > n_devices:
                raise ValueError(
                    f"mesh {data}x{prod} exceeds device count {n_devices}"
                )
        return {AXIS_DCN: dcn, AXIS_DATA: data, **fixed}


def build_mesh(
    spec: Optional[MeshSpec] = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a ``jax.sharding.Mesh`` with the canonical axis names.

    Axis order is (pipe, data, expert, seq, model): the innermost (fastest
    varying over physically-adjacent chips) axes are the ones with the most
    traffic per step — model/seq collectives every layer, data allreduce once
    per step, pipeline edges lightest — so `mesh_utils` places model/seq on
    ICI-adjacent chips.
    """
    spec = spec or MeshSpec()
    devices = list(devices if devices is not None else jax.devices())
    sizes = spec.resolve(len(devices), detect_slice_count(devices))
    order = (AXIS_DCN, AXIS_PIPE, AXIS_DATA, AXIS_EXPERT, AXIS_SEQ,
             AXIS_MODEL)
    shape = tuple(sizes[a] for a in order)
    total = int(np.prod(shape))
    if total < len(devices):
        # a sub-mesh is allowed in single-process runs (tests, debugging) but
        # would strand whole hosts' devices in a multi-process job while the
        # input pipeline still shards by process_count
        if jax.process_count() > 1:
            raise ValueError(
                f"mesh size {total} < device count {len(devices)} is not "
                "supported in multi-process runs")
        devices = devices[:total]
    dev_array = None
    if sizes[AXIS_DCN] > 1 and detect_slice_count(devices) == sizes[AXIS_DCN]:
        # real multislice: let mesh_utils keep each slice's sub-mesh on ICI
        # and put only the dcn axis across slice boundaries
        try:
            from jax.experimental import mesh_utils

            dev_array = mesh_utils.create_hybrid_device_mesh(
                (1,) + shape[1:],
                (shape[0],) + (1,) * (len(shape) - 1),
                devices=devices)
        except Exception:
            dev_array = None
    if dev_array is None:
        try:
            from jax.experimental import mesh_utils

            dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
        except Exception:
            # jax.devices() orders by process index, so a plain reshape
            # aligns the outermost (dcn) axis with process/slice boundaries
            dev_array = np.array(devices).reshape(shape)
    return Mesh(dev_array, order)


def mesh_fingerprint(mesh: Optional[Mesh] = None) -> str:
    """Stable identity of the device topology a process is running on —
    device count, platform/kind, and (when a mesh is given) the logical
    axis sizes.  Membership views (``resilience.membership``) carry the
    publisher's fingerprint so a replacement process brought up on
    DIFFERENT hardware (fewer chips, another generation) is rejected at
    rendezvous instead of wedging the first collective it joins."""
    devices = list(mesh.devices.flat) if mesh is not None else jax.devices()
    d0 = devices[0]
    parts = [str(len(devices)), getattr(d0, "platform", "?"),
             getattr(d0, "device_kind", "?")]
    if mesh is not None:
        parts.append("x".join(f"{a}={n}" for a, n in mesh.shape.items()))
    return ":".join(parts)


def data_axis_size(mesh: Mesh) -> int:
    return mesh.shape[AXIS_DATA]


def local_batch_slice(mesh: Mesh, global_batch: int) -> Tuple[int, int]:
    """(per-process batch start, size) for host-sharded input pipelines."""
    n_proc = jax.process_count()
    if global_batch % n_proc != 0:
        raise ValueError(f"global batch {global_batch} % processes {n_proc} != 0")
    per = global_batch // n_proc
    return jax.process_index() * per, per
