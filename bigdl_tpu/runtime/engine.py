"""Engine: process/topology bootstrap.

Reference analog (all unverified — mount empty): ``dllib/utils/Engine.scala``
reads executor topology from SparkConf, pins MKL threads/affinity, and builds
per-executor thread pools; ``Optimizer`` then refuses to run unless
``Engine.init`` succeeded.  TPU-native replacement: one Python process per
TPU-VM host (multi-controller), ``jax.distributed.initialize`` for rendezvous
(replacing the Spark driver/barrier control plane), and a ``Mesh`` built over
the slice.  There are no thread-pool model clones: per-host multi-chip
parallelism is XLA replication over the mesh.
"""

import dataclasses
import os
from dataclasses import dataclass
from typing import Optional, Union

import jax

from bigdl_tpu.resilience.retry import FailurePolicy
from bigdl_tpu.runtime.mesh import MeshSpec, build_mesh
from bigdl_tpu.utils.log import get_logger

log = get_logger("bigdl_tpu.engine")


@dataclass
class EngineConfig:
    """Typed config replacing the reference's three overlapping mechanisms
    (SparkConf props / ``bigdl.*`` sysprops / env soup — SURVEY.md §6.6)."""

    # multi-host rendezvous; None = single-process (or env-configured TPU pod)
    coordinator_address: Optional[str] = None
    num_processes: Optional[int] = None
    process_id: Optional[int] = None
    # logical mesh
    mesh: MeshSpec = dataclasses.field(default_factory=MeshSpec)
    # numerics
    compute_dtype: str = "bfloat16"  # matmul/conv compute dtype on TPU
    param_dtype: str = "float32"
    # failure handling (reference: bigdl.failure.retryTimes ~ 5, unverified).
    # failure_retry_times/interval bound the driver's cheap IN-RUN retry;
    # failure_policy is the full contract (per-cause retries, heartbeats,
    # watchdog) enforced by resilience.Supervisor around optimize().
    failure_retry_times: int = 5
    failure_retry_interval_s: float = 10.0
    failure_policy: Optional[FailurePolicy] = None
    # observability (docs/observability.md): profile_dir arms the
    # IterationProfiler over a warm window of every optimize() run;
    # metrics_port starts a standalone Prometheus /metrics endpoint for
    # jobs with no HTTP surface of their own (0 picks a free port).
    # metrics_host defaults loopback — a fleet scraper needs "0.0.0.0"
    # (set it deliberately: /metrics is unauthenticated)
    profile_dir: Optional[str] = None
    metrics_port: Optional[int] = None
    metrics_host: str = "127.0.0.1"
    # declarative SLOs (docs/observability.md §SLOs & burn rates): a list
    # of spec dicts ({"tenant", "objectives", "window_s"}), inline JSON,
    # or a JSON file path.  The Engine runs an SLOEvaluator over the
    # process registry for the process lifetime; burn rates export as
    # slo.* gauges on /metrics (pair with metrics_port for training
    # jobs).  BIGDL_TPU_SLO_SPECS overrides fleet-wide.
    slo_specs: Optional[object] = None
    # per-chip peak FLOP/s pin for the live train.mfu gauge
    # (docs/performance.md): needed when device_kind is missing from the
    # obs.cost table (new hardware, CPU test meshes).
    # BIGDL_TPU_PEAK_FLOPS overrides fleet-wide — resolved at call time
    # by obs.cost.peak_flops(), the env var's single owner, so it is NOT
    # parsed into this field by from_env().
    peak_flops: Optional[float] = None
    # input pipeline (docs/data.md): decode-worker pool width for the
    # streaming batch path; None = one per host core (capped in the
    # adapters).  BIGDL_TPU_DATA_WORKERS overrides fleet-wide.
    data_workers: Optional[int] = None
    # fused multi-step execution (docs/performance.md): compile K train
    # steps as ONE XLA program — the host re-enters Python once per
    # bundle, killing per-step dispatch overhead on small/fast models.
    # int K >= 1, or "auto" (the driver picks K from measured
    # dispatch-vs-step time after its first log window).
    # BIGDL_TPU_STEPS_PER_CALL overrides fleet-wide; the Optimizer's
    # steps_per_call attribute / Estimator "steps_per_call" config key
    # override per run.
    steps_per_call: Union[int, str] = 1
    # gradient-sync wire format (docs/parallelism.md §Gradient
    # compression): "fp32" (full precision), "bf16" (half the gradient
    # bytes), "int8" (blockwise-quantized — int8 payload + per-block
    # scales, ~4x fewer gradient bytes on ICI and DCN).  The Optimizer's
    # grad_comm attribute / Estimator "grad_comm" config key override per
    # run; BIGDL_TPU_GRAD_COMM overrides fleet-wide.
    grad_comm: str = "fp32"
    # gradient-sync bucketing (docs/parallelism.md): max flat-gradient
    # bytes per collective — smaller buckets give XLA's latency-hiding
    # scheduler independent scatter/update/gather chains to overlap;
    # None = one monolithic transfer.  BIGDL_TPU_COMM_BUCKET_BYTES
    # overrides fleet-wide.
    comm_bucket_bytes: Optional[int] = None
    # declarative parallelism policy (docs/parallelism.md §Declarative
    # layouts): a combo string like "dp" | "fsdp" | "tp:8" | "dp:4,tp:2"
    # resolved against the live device set into a named (data, fsdp, tp,
    # seq) mesh + per-model SpecLayout.  The Estimator/Keras
    # "parallelism" config key overrides per run; BIGDL_TPU_PARALLELISM
    # overrides fleet-wide.  None keeps the classic ZeRO-1 data-parallel
    # driver.
    parallelism: Optional[str] = None
    # kernel tile autotuning (docs/performance.md §Kernel autotuning):
    # "off" = hand-picked defaults only, "cache" = consult the on-disk
    # winner cache (default; never measures), "online" = measure-and-
    # cache on a miss (EAGER kernel calls only — jitted paths rely on
    # the offline CLI `python -m bigdl_tpu.ops.autotune`).
    # BIGDL_TPU_AUTOTUNE overrides fleet-wide — resolved at call time by
    # ops.autotune.autotune_mode(), the env var's single owner, so it is
    # NOT parsed into this field by from_env().
    kernel_autotune: str = "cache"

    def resolved_failure_policy(self) -> FailurePolicy:
        """The effective FailurePolicy: the explicit one, else defaults
        seeded from the legacy retry knobs (so BIGDL_TPU_RETRY_TIMES
        keeps meaning what it always did)."""
        if self.failure_policy is not None:
            return self.failure_policy
        from bigdl_tpu.resilience.retry import (FailureCause, RetryPolicy)

        # multiplier=1, no jitter, no cap: the legacy knob meant a FIXED
        # sleep between retries — deriving an exponential-capped policy
        # from it would silently change retry timing for existing
        # configs (e.g. interval_s=120 would hit the 60s cap and retry
        # twice as fast as configured)
        legacy = RetryPolicy(
            max_retries=self.failure_retry_times,
            base_s=self.failure_retry_interval_s,
            multiplier=1.0, jitter=0.0,
            max_s=self.failure_retry_interval_s)
        by_cause = {}
        if (self.failure_retry_times, self.failure_retry_interval_s) \
                != (5, 10.0):
            # the operator TUNED the legacy knobs: they override the
            # static per-cause storage defaults too — storage errors are
            # the dominant real cause on this path, and a tuned 120s
            # interval must not silently become a 0.5s exponential
            by_cause[FailureCause.TRANSIENT_STORAGE] = legacy
        return FailurePolicy(
            max_restarts=self.failure_retry_times,
            default_retry=legacy, by_cause=by_cause)

    @staticmethod
    def from_env() -> "EngineConfig":
        cfg = EngineConfig()
        if os.environ.get("BIGDL_TPU_COORDINATOR"):
            cfg.coordinator_address = os.environ["BIGDL_TPU_COORDINATOR"]
            cfg.num_processes = int(os.environ.get("BIGDL_TPU_NUM_PROCESSES", "1"))
            cfg.process_id = int(os.environ.get("BIGDL_TPU_PROCESS_ID", "0"))
        if os.environ.get("BIGDL_TPU_RETRY_TIMES"):
            cfg.failure_retry_times = int(os.environ["BIGDL_TPU_RETRY_TIMES"])
        if os.environ.get("BIGDL_TPU_HEARTBEAT_DIR"):
            # shared-visibility dir (same requirement as sharded ckpts):
            # enables peer liveness via resilience.detector heartbeats
            cfg.failure_policy = cfg.resolved_failure_policy()
            cfg.failure_policy.heartbeat_dir = \
                os.environ["BIGDL_TPU_HEARTBEAT_DIR"]
        if os.environ.get("BIGDL_TPU_CLUSTER_DIR"):
            # the full cluster control plane (docs/resilience.md
            # §Multi-host recovery): membership views, gang recovery, and
            # peer-shard restore over this shared directory — the
            # Supervisor builds a ClusterCoordinator from it
            cfg.failure_policy = cfg.failure_policy \
                or cfg.resolved_failure_policy()
            cfg.failure_policy.cluster_dir = \
                os.environ["BIGDL_TPU_CLUSTER_DIR"]
        if os.environ.get("BIGDL_TPU_PROFILE_DIR"):
            cfg.profile_dir = os.environ["BIGDL_TPU_PROFILE_DIR"]
        if os.environ.get("BIGDL_TPU_METRICS_PORT"):
            cfg.metrics_port = int(os.environ["BIGDL_TPU_METRICS_PORT"])
        if os.environ.get("BIGDL_TPU_METRICS_HOST"):
            cfg.metrics_host = os.environ["BIGDL_TPU_METRICS_HOST"]
        if os.environ.get("BIGDL_TPU_SLO_SPECS"):
            cfg.slo_specs = os.environ["BIGDL_TPU_SLO_SPECS"]
        if os.environ.get("BIGDL_TPU_DATA_WORKERS"):
            cfg.data_workers = int(os.environ["BIGDL_TPU_DATA_WORKERS"])
        if os.environ.get("BIGDL_TPU_PARALLELISM"):
            # validated lazily at resolve time (the live device count is
            # not known until the backend initializes); bad axis names
            # still fail fast there with the full grammar in the message
            cfg.parallelism = \
                os.environ["BIGDL_TPU_PARALLELISM"].strip().lower()
        if os.environ.get("BIGDL_TPU_GRAD_COMM"):
            cfg.grad_comm = os.environ["BIGDL_TPU_GRAD_COMM"].strip().lower()
        if os.environ.get("BIGDL_TPU_COMM_BUCKET_BYTES"):
            cfg.comm_bucket_bytes = int(
                os.environ["BIGDL_TPU_COMM_BUCKET_BYTES"])
        if os.environ.get("BIGDL_TPU_STEPS_PER_CALL"):
            raw = os.environ["BIGDL_TPU_STEPS_PER_CALL"].strip().lower()
            cfg.steps_per_call = "auto" if raw == "auto" else int(raw)
        if os.environ.get("BIGDL_TPU_DCN_SLICES"):
            # force the cross-slice data-parallel degree where the runtime
            # exposes no slice topology (e.g. multi-host CPU, GKE multislice
            # before the plugin reports slice_index)
            cfg.mesh = dataclasses.replace(
                cfg.mesh, dcn_data=int(os.environ["BIGDL_TPU_DCN_SLICES"]))
        return cfg


class Engine:
    """Singleton runtime: initialized once per process, owns the global mesh."""

    _instance: Optional["Engine"] = None

    _distributed_initialized = False

    def __init__(self, config: EngineConfig):
        self.config = config
        # BIGDL_TPU_PLATFORM=cpu forces the host platform even where a TPU
        # plugin ignores the JAX_PLATFORMS env var (combine with
        # XLA_FLAGS=--xla_force_host_platform_device_count=N for a simulated
        # mesh — the reference's local[N] analog, SURVEY.md §5)
        plat = os.environ.get("BIGDL_TPU_PLATFORM")
        if plat:
            try:
                jax.config.update("jax_platforms", plat)
            except RuntimeError:
                log.warning("backend already initialized; "
                            "BIGDL_TPU_PLATFORM=%s ignored", plat)
        if config.coordinator_address is not None and not Engine._distributed_initialized:
            jax.distributed.initialize(
                coordinator_address=config.coordinator_address,
                num_processes=config.num_processes,
                process_id=config.process_id,
            )
            Engine._distributed_initialized = True
        self.mesh = build_mesh(config.mesh)
        self.metrics_server = None
        if config.metrics_port is not None:
            # training jobs have no serving frontend to hang /metrics on;
            # the engine owns the scrape endpoint instead.  A bind failure
            # (port in use — a second job on the host, a pool worker that
            # inherited the env) degrades observability, never compute
            from bigdl_tpu.obs.export import MetricsServer

            try:
                self.metrics_server = MetricsServer(
                    host=config.metrics_host,
                    port=config.metrics_port).start()
            except OSError as e:
                log.error("metrics server failed to bind %s:%s (%s); "
                          "continuing WITHOUT a /metrics endpoint",
                          config.metrics_host, config.metrics_port, e)
        self.slo_evaluator = None
        if config.slo_specs is not None:
            # process-lifetime burn-rate evaluation over the global
            # registry; a bad spec degrades observability, never compute
            from bigdl_tpu.obs.slo import SLOEvaluator

            try:
                self.slo_evaluator = SLOEvaluator(
                    config.slo_specs).start()
            except Exception as e:  # noqa: BLE001
                log.error("SLO specs unusable (%s); SLO evaluation "
                          "disabled", e)
        log.info(
            "Engine initialized: %d devices (%s), %d processes, mesh %s",
            jax.device_count(),
            jax.devices()[0].platform,
            jax.process_count(),
            dict(self.mesh.shape),
        )

    # -- singleton plumbing -------------------------------------------------
    @classmethod
    def get(cls) -> "Engine":
        if cls._instance is None:
            cls._instance = Engine(EngineConfig.from_env())
        return cls._instance

    @classmethod
    def reset(cls) -> None:
        if cls._instance is not None:
            if cls._instance.metrics_server is not None:
                cls._instance.metrics_server.stop()
            if getattr(cls._instance, "slo_evaluator", None) is not None:
                cls._instance.slo_evaluator.stop()
        cls._instance = None

    @property
    def node_number(self) -> int:
        return jax.process_count()

    @property
    def core_number(self) -> int:
        """Devices per process — the analog of coresPerExecutor."""
        return jax.local_device_count()


def init_engine(config: Optional[EngineConfig] = None, **mesh_axes) -> Engine:
    """Initialize (or re-initialize) the global Engine.

    ``init_engine(model=2)`` resizes the logical mesh; the analog of
    ``Engine.init`` + ``spark-bigdl.conf`` in the reference.
    """
    if config is None:
        config = EngineConfig.from_env()
    if mesh_axes:
        config.mesh = dataclasses.replace(config.mesh, **mesh_axes)
    Engine._instance = Engine(config)
    return Engine._instance


def enable_compile_cache(cache_dir: Optional[str] = None) -> None:
    """Turn on JAX's persistent compilation cache (an optimization, never a
    failure — errors are swallowed).  Big-model XLA compiles take minutes on
    tunneled chips; the cache makes re-runs near-instant."""
    if cache_dir is None:
        cache_dir = os.path.join(os.getcwd(), ".jax_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:  # pragma: no cover — older jax without the options
        pass


def force_cpu_devices(n_devices: int = 8) -> None:
    """Force the CPU platform with ``n_devices`` virtual devices — the
    ``local[N]`` simulated-mesh bootstrap (SURVEY.md §5).

    Gotcha this wraps (one place instead of three): this image's axon TPU
    plugin IGNORES the ``JAX_PLATFORMS`` env var, so the platform must be
    forced via ``jax.config.update`` — and ``XLA_FLAGS`` must carry the
    virtual-device count BEFORE the backend initializes.  Call before any
    ``jax.devices()``/array op."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    jax.config.update("jax_platforms", "cpu")
