"""ctypes bindings for the native host-side IO/vision library.

Reference analog: the JNI façade classes (``com.intel.analytics.bigdl.
opencv.OpenCV``, ``mkl.MKL`` — SURVEY.md §3.2) that expose BigDL-core's
``.so`` to the JVM.  Here: a C ABI (``native/bigdl_tpu_io.cpp``) compiled
on first use with the system g++ and loaded via ctypes; every entry point
has a pure-numpy fallback so the package works where no toolchain exists
(mirroring the reference's pure-JVM fallback when MKL is absent).

Public surface: ``available()``, ``resize_bilinear``, ``normalize``,
``hflip``, ``crop``, ``decode_jpeg``, ``jpeg_available``,
``BatchPipeline`` (threaded decode/transform→assemble).
"""

from bigdl_tpu.native.lib import (BatchPipeline, available, crop,
                                  decode_jpeg, hflip, jpeg_available,
                                  normalize, resize_bilinear)

__all__ = ["available", "resize_bilinear", "normalize", "hflip", "crop",
           "decode_jpeg", "jpeg_available", "BatchPipeline"]
