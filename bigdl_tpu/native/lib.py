"""Build-on-first-use loader + ctypes wrappers + numpy fallbacks."""

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_SRC = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), "native", "bigdl_tpu_io.cpp")
_CACHE_DIR = os.environ.get(
    "BIGDL_TPU_NATIVE_CACHE",
    os.path.join(os.path.expanduser("~"), ".cache", "bigdl_tpu"))

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build_and_load() -> Optional[ctypes.CDLL]:
    if not os.path.exists(_SRC):
        return None
    os.makedirs(_CACHE_DIR, exist_ok=True)
    so = os.path.join(_CACHE_DIR, "libbigdl_tpu_io.so")
    if (not os.path.exists(so)
            or os.path.getmtime(so) < os.path.getmtime(_SRC)):
        base = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC",
                "-march=native", "-o", so + ".tmp", _SRC, "-lpthread"]
        # with libjpeg if the box has it; every other op still builds
        # without (python decode falls back to PIL)
        for cmd in (base + ["-ljpeg"],
                    base[:-1] + ["-DBTIO_NO_JPEG", "-lpthread"]):
            try:
                subprocess.run(cmd, check=True, capture_output=True,
                               timeout=120)
                os.replace(so + ".tmp", so)
                break
            except (subprocess.SubprocessError, OSError):
                continue
        else:
            return None
    try:
        lib = ctypes.CDLL(so)
    except OSError:
        return None
    # signatures
    u8p = ctypes.POINTER(ctypes.c_uint8)
    f32p = ctypes.POINTER(ctypes.c_float)
    i64p = ctypes.POINTER(ctypes.c_int64)
    i32p = ctypes.POINTER(ctypes.c_int32)
    lib.btio_resize_bilinear_u8.argtypes = [
        u8p, ctypes.c_int, ctypes.c_int, ctypes.c_int, u8p, ctypes.c_int,
        ctypes.c_int]
    lib.btio_crop_u8.argtypes = [
        u8p, ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, u8p, ctypes.c_int, ctypes.c_int]
    lib.btio_hflip_u8.argtypes = [u8p, ctypes.c_int, ctypes.c_int,
                                  ctypes.c_int]
    lib.btio_normalize_f32.argtypes = [
        u8p, ctypes.c_int, ctypes.c_int, ctypes.c_int, f32p, f32p, f32p]
    lib.btio_pipeline_create.argtypes = [ctypes.c_int]
    lib.btio_pipeline_create.restype = ctypes.c_void_p
    lib.btio_pipeline_destroy.argtypes = [ctypes.c_void_p]
    lib.btio_process_batch.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(u8p), i32p, i32p,
        ctypes.c_int, ctypes.c_int, ctypes.c_int, f32p, f32p, f32p]
    lib.btio_gather_rows_f32.argtypes = [
        ctypes.c_void_p, f32p, i64p, ctypes.c_int, ctypes.c_int64, f32p]
    lib.btio_records_open.argtypes = [ctypes.c_char_p]
    lib.btio_records_open.restype = ctypes.c_void_p
    lib.btio_records_count.argtypes = [ctypes.c_void_p]
    lib.btio_records_count.restype = ctypes.c_int64
    lib.btio_records_bytes.argtypes = [ctypes.c_void_p]
    lib.btio_records_bytes.restype = ctypes.c_int64
    lib.btio_records_gather.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, i64p, ctypes.c_int, u8p]
    lib.btio_records_close.argtypes = [ctypes.c_void_p]
    lib.btio_jpeg_available.restype = ctypes.c_int
    lib.btio_jpeg_dims.argtypes = [u8p, ctypes.c_int64, i32p, i32p, i32p]
    lib.btio_jpeg_dims.restype = ctypes.c_int
    lib.btio_jpeg_decode.argtypes = [u8p, ctypes.c_int64, u8p, ctypes.c_int,
                                     ctypes.c_int]
    lib.btio_jpeg_decode.restype = ctypes.c_int
    lib.btio_decode_batch.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(u8p), i64p, i32p,
        ctypes.c_int, ctypes.c_int, f32p, f32p, f32p, i32p]
    lib.btio_version.restype = ctypes.c_int
    if lib.btio_version() != 4:
        return None
    return lib


def _get() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is None and not _tried:
        with _lock:
            if _lib is None and not _tried:
                _lib = _build_and_load()
                _tried = True
    return _lib


def available() -> bool:
    return _get() is not None


def _u8p(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _f32p(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


# ---------------------------------------------------------------------------
# Single-image ops (uint8 HWC)
# ---------------------------------------------------------------------------

def resize_bilinear(img: np.ndarray, oh: int, ow: int) -> np.ndarray:
    img = np.ascontiguousarray(img, np.uint8)
    h, w, c = img.shape
    if (h, w) == (oh, ow):
        return img
    lib = _get()
    if lib is not None:
        out = np.empty((oh, ow, c), np.uint8)
        lib.btio_resize_bilinear_u8(_u8p(img), h, w, c, _u8p(out), oh, ow)
        return out
    # numpy fallback (same align-corners-style sampling as the C path)
    ys = (np.linspace(0, h - 1, oh) if oh > 1 else np.zeros(1))
    xs = (np.linspace(0, w - 1, ow) if ow > 1 else np.zeros(1))
    y0 = ys.astype(np.int64)
    x0 = xs.astype(np.int64)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    wy = (ys - y0)[:, None, None]
    wx = (xs - x0)[None, :, None]
    f = img.astype(np.float32)
    top = f[y0][:, x0] * (1 - wx) + f[y0][:, x1] * wx
    bot = f[y1][:, x0] * (1 - wx) + f[y1][:, x1] * wx
    return np.rint(top * (1 - wy) + bot * wy).astype(np.uint8)


def _check_crop(h, w, oy, ox, ch, cw):
    if oy < 0 or ox < 0 or oy + ch > h or ox + cw > w:
        raise ValueError(
            f"crop ({ch}x{cw} at {oy},{ox}) out of bounds for {h}x{w} image"
            " — resize up first (the C path would read out of bounds)")


def crop(img: np.ndarray, oy: int, ox: int, ch: int, cw: int) -> np.ndarray:
    img = np.ascontiguousarray(img, np.uint8)
    h, w, c = img.shape
    _check_crop(h, w, oy, ox, ch, cw)
    lib = _get()
    if lib is not None:
        out = np.empty((ch, cw, c), np.uint8)
        lib.btio_crop_u8(_u8p(img), h, w, c, oy, ox, _u8p(out), ch, cw)
        return out
    return img[oy:oy + ch, ox:ox + cw].copy()


def hflip(img: np.ndarray) -> np.ndarray:
    src = np.asarray(img)
    out = np.ascontiguousarray(src, np.uint8)
    if out is src:  # ascontiguousarray didn't copy — keep input unmutated
        out = out.copy()
    lib = _get()
    if lib is not None:
        h, w, c = out.shape
        lib.btio_hflip_u8(_u8p(out), h, w, c)
        return out
    return out[:, ::-1].copy()


def normalize(img: np.ndarray, mean, std) -> np.ndarray:
    """uint8 HWC -> float32 HWC, (x/255 - mean) / std per channel."""
    img = np.ascontiguousarray(img, np.uint8)
    h, w, c = img.shape
    mean = np.ascontiguousarray(mean, np.float32)
    std = np.ascontiguousarray(std, np.float32)
    lib = _get()
    if lib is not None:
        out = np.empty((h, w, c), np.float32)
        lib.btio_normalize_f32(_u8p(img), h, w, c, _f32p(mean), _f32p(std),
                               _f32p(out))
        return out
    return ((img.astype(np.float32) / 255.0 - mean) / std).astype(np.float32)


# ---------------------------------------------------------------------------
# Threaded batch pipeline
# ---------------------------------------------------------------------------

class BatchPipeline:
    """Threaded per-image transform → contiguous NHWC f32 batch assembly.

    Reference analog: per-executor ``ThreadPool.invokeAndWait`` over
    transformer chains inside ``SampleToMiniBatch`` (SURVEY.md §4.1)."""

    def __init__(self, num_threads: Optional[int] = None):
        self.num_threads = num_threads or max(1, (os.cpu_count() or 2) - 1)
        lib = _get()
        self._lib = lib
        self._pipe = (lib.btio_pipeline_create(self.num_threads)
                      if lib is not None else None)

    def close(self):
        if self._pipe is not None:
            self._lib.btio_pipeline_destroy(self._pipe)
            self._pipe = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    @staticmethod
    def _out_buffer(out, n, oh, ow, c) -> np.ndarray:
        """Validate a caller-provided output buffer (a ring slot — the
        no-per-batch-allocation path of data/pipeline.py) or allocate one."""
        if out is None:
            return np.empty((n, oh, ow, c), np.float32)
        if out.shape != (n, oh, ow, c) or out.dtype != np.float32 \
                or not out.flags.c_contiguous:
            raise ValueError(
                f"out buffer must be C-contiguous float32 {(n, oh, ow, c)}, "
                f"got {out.dtype} {out.shape}")
        return out

    def process_batch(self, images, out_hw, mean, std, resize_hw=None,
                      crops=None, flips=None, out=None) -> np.ndarray:
        """images: list of uint8 HWC arrays (same channel count).
        out_hw: (oh, ow) final size.  resize_hw: per-image or single (rh, rw)
        intermediate resize (None = no resize).  crops: per-image (cy, cx)
        offsets (None = 0,0).  flips: per-image bool (None = no flip).
        out: optional preallocated (n, oh, ow, c) float32 destination
        (a reusable ring slot); allocated fresh when None.
        Returns (n, oh, ow, c) float32, normalized."""
        n = len(images)
        oh, ow = out_hw
        c = images[0].shape[2]
        mean = np.ascontiguousarray(mean, np.float32)
        std = np.ascontiguousarray(std, np.float32)
        images = [np.ascontiguousarray(im, np.uint8) for im in images]

        if self._pipe is not None:
            out = self._out_buffer(out, n, oh, ow, c)
            srcs = (ctypes.POINTER(ctypes.c_uint8) * n)(
                *[_u8p(im) for im in images])
            dims = np.empty((n, 2), np.int32)
            geom = np.zeros((n, 5), np.int32)
            for i, im in enumerate(images):
                dims[i] = im.shape[:2]
                eh, ew = im.shape[:2]  # size entering the crop stage
                if resize_hw is not None:
                    rh, rw = (resize_hw[i]
                              if not np.isscalar(resize_hw[0]) else resize_hw)
                    geom[i, 0], geom[i, 1] = rh, rw
                    eh, ew = rh, rw
                cy, cx = crops[i] if crops is not None else (0, 0)
                _check_crop(eh, ew, cy, cx, oh, ow)
                if crops is not None:
                    geom[i, 2], geom[i, 3] = crops[i]
                if flips is not None:
                    geom[i, 4] = int(bool(flips[i]))
            self._lib.btio_process_batch(
                self._pipe, n, srcs,
                dims.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                geom.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                c, oh, ow, _f32p(mean), _f32p(std), _f32p(out))
            return out

        # fallback: sequential numpy
        out = self._out_buffer(out, n, oh, ow, c)
        for i, im in enumerate(images):
            cur = im
            if resize_hw is not None:
                rh, rw = (resize_hw[i]
                          if not np.isscalar(resize_hw[0]) else resize_hw)
                cur = resize_bilinear(cur, rh, rw)
            cy, cx = crops[i] if crops is not None else (0, 0)
            _check_crop(cur.shape[0], cur.shape[1], cy, cx, oh, ow)
            if cur.shape[:2] != (oh, ow) or (cy, cx) != (0, 0):
                cur = cur[cy:cy + oh, cx:cx + ow]
            if flips is not None and flips[i]:
                cur = cur[:, ::-1]
            out[i] = (cur.astype(np.float32) / 255.0 - mean) / std
        return out

    def decode_batch(self, encoded, out_hw, mean, std, resize_hw=None,
                     crops=None, flips=None, out=None) -> np.ndarray:
        """JPEG decode + transform, fully in C++ worker threads.

        ``encoded``: list of ``bytes`` (JPEG).  Remaining args as in
        ``process_batch`` (including the ``out=`` ring-slot destination).
        Returns (n, oh, ow, 3) float32.  Falls back to PIL +
        ``process_batch`` when the native lib lacks libjpeg.
        Raises ValueError naming the failing index on a corrupt image."""
        n = len(encoded)
        oh, ow = out_hw
        if self._pipe is None or not jpeg_available():
            return self.process_batch([decode_jpeg(e) for e in encoded],
                                      out_hw, mean, std, resize_hw=resize_hw,
                                      crops=crops, flips=flips, out=out)
        mean = np.ascontiguousarray(mean, np.float32)
        std = np.ascontiguousarray(std, np.float32)
        bufs = [np.frombuffer(e, np.uint8) for e in encoded]
        srcs = (ctypes.POINTER(ctypes.c_uint8) * n)(
            *[_u8p(b) for b in bufs])
        lens = np.asarray([len(e) for e in encoded], np.int64)
        geom = np.zeros((n, 5), np.int32)
        for i in range(n):
            if resize_hw is not None:
                rh, rw = (resize_hw[i]
                          if not np.isscalar(resize_hw[0]) else resize_hw)
                geom[i, 0], geom[i, 1] = rh, rw
            if crops is not None:
                geom[i, 2], geom[i, 3] = crops[i]
            if flips is not None:
                geom[i, 4] = int(bool(flips[i]))
        out = self._out_buffer(out, n, oh, ow, 3)
        status = np.empty((n,), np.int32)
        self._lib.btio_decode_batch(
            self._pipe, n, srcs,
            lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            geom.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            oh, ow, _f32p(mean), _f32p(std), _f32p(out),
            status.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        bad_decode = np.flatnonzero(status == -1)
        bad_crop = np.flatnonzero(status == -2)
        if len(bad_crop):
            raise ValueError(
                "crop out of bounds of the decoded/resized image for batch "
                f"indices {bad_crop.tolist()[:8]} — pass resize_hw or "
                "shrink the crop (geometry bug, not corrupt data)")
        if len(bad_decode):
            raise ValueError(
                f"JPEG decode failed for batch indices "
                f"{bad_decode.tolist()[:8]}")
        return out

    def gather_rows(self, src: np.ndarray, idx: np.ndarray) -> np.ndarray:
        """Parallel src[idx] for a 2-D-viewable float32 array (batch
        assembly from a sample pool)."""
        src = np.ascontiguousarray(src, np.float32)
        idx = np.ascontiguousarray(idx, np.int64)
        if self._pipe is None:
            return src[idx].copy()
        row = int(np.prod(src.shape[1:]))
        out = np.empty((len(idx),) + src.shape[1:], np.float32)
        self._lib.btio_gather_rows_f32(
            self._pipe, _f32p(src),
            idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(idx), row, _f32p(out))
        return out


class RecordReader:
    """Memory-mapped fixed-size-record reader over the native lib (the
    data-loader executor) with threaded batch gather; ``None`` handle when
    the lib is unavailable (callers fall back to np.memmap)."""

    def __init__(self, path: str, pipeline: "BatchPipeline" = None):
        lib = _get()
        self._lib = lib
        self._h = lib.btio_records_open(
            os.fsencode(path)) if lib is not None else None
        if lib is not None and not self._h:
            raise ValueError(f"not a BTRECv1 record file: {path}")
        self._pipe = pipeline

    @property
    def ok(self) -> bool:
        return self._h is not None

    def count(self) -> int:
        return int(self._lib.btio_records_count(self._h))

    def record_bytes(self) -> int:
        return int(self._lib.btio_records_bytes(self._h))

    def gather(self, idx: np.ndarray, out=None) -> np.ndarray:
        """(n,) int64 indices -> (n, record_bytes) uint8.  ``out``: optional
        preallocated destination (a reusable read-stage buffer)."""
        idx = np.ascontiguousarray(idx, np.int64)
        shape = (len(idx), self.record_bytes())
        if out is None:
            out = np.empty(shape, np.uint8)
        elif out.shape != shape or out.dtype != np.uint8 \
                or not out.flags.c_contiguous:
            raise ValueError(
                f"out buffer must be C-contiguous uint8 {shape}, got "
                f"{out.dtype} {out.shape}")
        self._lib.btio_records_gather(
            self._h, self._pipe._pipe if self._pipe is not None else None,
            idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), len(idx),
            _u8p(out))
        return out

    def close(self):
        if self._h is not None:
            self._lib.btio_records_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def jpeg_available() -> bool:
    """True when the native lib was built against libjpeg."""
    lib = _get()
    return bool(lib is not None and lib.btio_jpeg_available())


def decode_jpeg(data: bytes) -> np.ndarray:
    """Decode one JPEG to (h, w, 3) RGB uint8 — native libjpeg when
    available, PIL otherwise.  Raises ValueError on corrupt input."""
    lib = _get()
    if lib is not None and lib.btio_jpeg_available():
        buf = np.frombuffer(data, np.uint8)
        h = ctypes.c_int32()
        w = ctypes.c_int32()
        c = ctypes.c_int32()
        i32p_ = ctypes.POINTER(ctypes.c_int32)
        if lib.btio_jpeg_dims(_u8p(buf), len(data), ctypes.byref(h),
                              ctypes.byref(w), ctypes.byref(c)) == 0:
            out = np.empty((h.value, w.value, 3), np.uint8)
            if lib.btio_jpeg_decode(_u8p(buf), len(data), _u8p(out),
                                    h.value, w.value) == 0:
                return out
        raise ValueError("corrupt or unsupported JPEG")
    import io

    from PIL import Image

    try:
        with Image.open(io.BytesIO(data)) as im:
            return np.asarray(im.convert("RGB"), np.uint8)
    except Exception as e:
        raise ValueError(f"corrupt or unsupported JPEG: {e}") from None
