"""AutoML — hyperparameter search.

Reference analog (unverified — mount empty): ``python/orca/src/bigdl/orca/
automl/`` (SURVEY.md §3.3): ``AutoEstimator.fit(data, search_space,
n_sampling)`` running trials on Ray Tune with the ``hp`` search-space DSL.

TPU-native redesign of Ray Tune's actor concurrency (three modes):

- **sequential** (default): one trial at a time with the WHOLE mesh — the
  right mode when each trial is itself a distributed (sharded) train step:
  a TPU slice is gang-scheduled to one program, and jit caching makes
  same-shape trials cheap.
- **per-device parallel** (``run(..., parallel=k | "auto")``): waves of k
  concurrent trials on a thread pool, each pinned to its own device via
  ``trial_device(config)`` — the actor-pool analog for single-device
  trials on a multi-chip mesh (XLA releases the GIL during execution).
  ASHA rungs run their members concurrently.
- **vmapped gang** (``vmap_sweep``): numeric-hyperparameter configs
  stacked and evaluated inside ONE jitted, device-sharded vmap — the
  fully XLA-native sweep when the trial is a pure jax function with
  config-independent shapes.

The ``hp`` DSL and the Searcher/AutoEstimator surface mirror the reference
so AutoTS code ports unchanged.
"""

from bigdl_tpu.automl import hp
from bigdl_tpu.automl.auto_estimator import AutoEstimator
from bigdl_tpu.automl.search import (GridSearcher, RandomSearcher, Searcher,
                                     SuccessiveHalvingSearcher, TPESearcher,
                                     TrialResult, trial_device, vmap_sweep)

__all__ = ["hp", "AutoEstimator", "Searcher", "RandomSearcher",
           "GridSearcher", "SuccessiveHalvingSearcher", "TPESearcher",
           "TrialResult", "trial_device", "vmap_sweep"]
