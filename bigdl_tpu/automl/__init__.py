"""AutoML — hyperparameter search.

Reference analog (unverified — mount empty): ``python/orca/src/bigdl/orca/
automl/`` (SURVEY.md §3.3): ``AutoEstimator.fit(data, search_space,
n_sampling)`` running trials on Ray Tune with the ``hp`` search-space DSL.

TPU-native redesign: trials run sequentially in-process — a TPU slice is
gang-scheduled to ONE program, so concurrent trials would fight for the
chips; sequential trials each get the whole mesh (and jit caching makes
same-shape trials cheap).  The ``hp`` DSL and the Searcher/AutoEstimator
surface mirror the reference so AutoTS code ports unchanged.
"""

from bigdl_tpu.automl import hp
from bigdl_tpu.automl.auto_estimator import AutoEstimator
from bigdl_tpu.automl.search import (GridSearcher, RandomSearcher, Searcher,
                                     SuccessiveHalvingSearcher, TPESearcher,
                                     TrialResult)

__all__ = ["hp", "AutoEstimator", "Searcher", "RandomSearcher",
           "GridSearcher", "SuccessiveHalvingSearcher", "TPESearcher",
           "TrialResult"]
