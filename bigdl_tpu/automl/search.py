"""Trial searchers — reference ``orca/automl/search/`` (Ray Tune runs
trials as concurrent actors there).  Here trials run in-process with two
concurrency modes replacing the actor pool:

- ``parallel=k`` on ``run``: waves of ``k`` trials on a thread pool, each
  trial pinned to its own device of the mesh via ``trial_device`` (XLA
  releases the GIL during execution, so k single-device trials execute
  concurrently on k chips — the per-device-trial mode).  Adaptive
  searchers (TPE) propose between waves, the standard batched form;
  successive halving parallelizes within each rung.
- ``vmap_sweep``: numeric-axis configs stacked and evaluated inside ONE
  jitted, device-sharded vmap — the gang mode for trials expressible as a
  pure jax function (shapes must agree across configs).
"""

import dataclasses
import threading
import time
import traceback
from concurrent.futures import ThreadPoolExecutor
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from bigdl_tpu.automl import hp as hp_mod
from bigdl_tpu.utils.log import get_logger

log = get_logger(__name__)


@contextmanager
def trial_device(config: Dict[str, Any]):
    """Pin this trial's computations to the device assigned by the parallel
    runner (``config["_device_index"]``); no-op for sequential runs."""
    import jax

    idx = config.get("_device_index")
    if idx is None:
        yield None
        return
    dev = jax.devices()[idx % jax.device_count()]
    with jax.default_device(dev):
        yield dev


@dataclasses.dataclass
class TrialResult:
    config: Dict[str, Any]
    metric: float
    artifacts: Any = None          # whatever the trial fn returned alongside
    duration_s: float = 0.0
    error: Optional[str] = None


class Searcher:
    """Drive trial_fn(config) -> (metric, artifacts) over a search space."""

    def __init__(self, mode: str = "min"):
        assert mode in ("min", "max")
        self.mode = mode
        self.results: List[TrialResult] = []

    def _configs(self, space, n_sampling):
        raise NotImplementedError

    _lock = None  # created lazily; Searcher instances are not shared wide

    def _run_one(self, trial_fn, config, sign) -> TrialResult:
        """Execute one trial: time it, unpack (metric, artifacts), convert
        failures into an inf-metric result (a bad config must not kill the
        sweep).  Appends to self.results (thread-safe)."""
        t0 = time.perf_counter()
        try:
            out = trial_fn(config)
            metric, artifacts = out if isinstance(out, tuple) else (out, None)
            metric = float(metric)
            if not np.isfinite(metric):
                # a diverged trial (NaN/inf loss) must never win a sort —
                # NaN compares False against everything and would float to
                # the top of a sorted() ranking
                res = TrialResult(config, float("inf") * sign, None,
                                  time.perf_counter() - t0,
                                  error=f"non-finite metric: {metric}")
            else:
                res = TrialResult(config, metric, artifacts,
                                  time.perf_counter() - t0)
        except Exception:  # noqa: BLE001
            res = TrialResult(config, float("inf") * sign, None,
                              time.perf_counter() - t0,
                              error=traceback.format_exc())
            log.warning("trial failed: %s", res.error.splitlines()[-1])
        if self._lock is None:
            self._lock = threading.Lock()
        with self._lock:
            self.results.append(res)
        return res

    def _run_wave(self, trial_fn, configs, sign, parallel) -> List[TrialResult]:
        """Run a batch of trials, concurrently when parallel > 1; each slot
        carries a device assignment for ``trial_device``."""
        if parallel <= 1 or len(configs) <= 1:
            return [self._run_one(trial_fn, c, sign) for c in configs]
        cfgs = [dict(c, _device_index=i % parallel)
                for i, c in enumerate(configs)]
        with ThreadPoolExecutor(max_workers=parallel) as ex:
            return list(ex.map(
                lambda c: self._run_one(trial_fn, c, sign), cfgs))

    @staticmethod
    def _resolve_parallel(parallel) -> int:
        if parallel in (None, 0, 1):
            return 1
        if parallel == "auto":
            import jax

            return jax.device_count()
        return int(parallel)

    def run(self, trial_fn: Callable[[Dict], Any], space: Dict[str, Any],
            n_sampling: int = 8, parallel=None) -> TrialResult:
        """``parallel``: None/1 = sequential; k = waves of k concurrent
        trials (one per device); "auto" = one per local device.  Adaptive
        searchers observe between waves (batched proposals)."""
        sign = 1.0 if self.mode == "min" else -1.0
        par = self._resolve_parallel(parallel)
        best = None
        it = iter(self._configs(space, n_sampling))
        done = 0
        # n_sampling == 0 means "whatever _configs yields" (grid caps only
        # when asked) — run until the generator is exhausted
        limit = n_sampling if n_sampling else None
        while limit is None or done < limit:
            room = par if limit is None else min(par, limit - done)
            wave = []
            for _ in range(room):
                try:
                    wave.append(next(it))
                except StopIteration:
                    break
            if not wave:
                break
            for res in self._run_wave(trial_fn, wave, sign, par):
                done += 1
                if res.error is None and (
                        best is None
                        or sign * res.metric < sign * best.metric):
                    if best is not None:
                        best.artifacts = None  # only the winner's model kept
                    best = res
                else:
                    res.artifacts = None
                log.info("trial %d/%s: metric=%s config=%s", done,
                         n_sampling, res.metric, res.config)
        if best is None:
            raise RuntimeError("all trials failed; see results[*].error")
        return best


class RandomSearcher(Searcher):
    def __init__(self, mode: str = "min", seed: int = 0):
        super().__init__(mode)
        self.rng = np.random.default_rng(seed)

    def _configs(self, space, n_sampling):
        for _ in range(n_sampling):
            yield hp_mod.sample_space(space, self.rng)


class GridSearcher(Searcher):
    """Exhaustive over discrete axes; n_sampling caps the trial count."""

    def _configs(self, space, n_sampling):
        pts = hp_mod.grid_points(space)
        return pts[:n_sampling] if n_sampling else pts


class SuccessiveHalvingSearcher(Searcher):
    """Successive halving (ASHA-style, synchronous rungs) — the reference's
    AutoML uses Ray Tune schedulers of this family.

    The trial budget (e.g. epochs) is injected into the config under
    ``budget_key``; ``trial_fn`` must honor it.  ``n_sampling`` configs start
    at ``min_budget``; each rung keeps the top ``1/eta`` and multiplies the
    budget by ``eta`` until ``max_budget``."""

    def __init__(self, mode: str = "min", seed: int = 0, eta: int = 3,
                 min_budget: int = 1, max_budget: int = 9,
                 budget_key: str = "epochs"):
        super().__init__(mode)
        self.rng = np.random.default_rng(seed)
        self.eta = int(eta)
        self.min_budget = int(min_budget)
        self.max_budget = int(max_budget)
        self.budget_key = budget_key

    def run(self, trial_fn, space, n_sampling: int = 9,
            parallel=None) -> TrialResult:
        sign = 1.0 if self.mode == "min" else -1.0
        par = self._resolve_parallel(parallel)
        configs = [hp_mod.sample_space(space, self.rng)
                   for _ in range(n_sampling)]
        budget = self.min_budget
        survivors = configs
        best = None  # best of the HIGHEST rung reached — metrics at
        rung = 0     # different budgets are not comparable
        while True:
            # a rung is an independent batch: all its trials run
            # concurrently (the reference's ASHA runs rung members as
            # parallel Ray actors)
            cfgs = [dict(c, **{self.budget_key: budget}) for c in survivors]
            results = self._run_wave(trial_fn, cfgs, sign, par)
            scored = list(zip(results, survivors))
            scored.sort(key=lambda rc: sign * rc[0].metric)
            for res, _ in scored[1:]:
                res.artifacts = None
            if scored[0][0].error is None:
                if best is not None:
                    best.artifacts = None
                best = scored[0][0]  # this rung's winner supersedes
            log.info("rung %d (budget=%d): best=%s", rung, budget,
                     scored[0][0].metric)
            if budget >= self.max_budget:
                break
            keep = max(1, len(scored) // self.eta)
            survivors = [c for _, c in scored[:keep]]
            budget = min(budget * self.eta, self.max_budget)
            rung += 1
        if best is None:
            raise RuntimeError("all trials failed; see results[*].error")
        return best


class TPESearcher(Searcher):
    """Tree-structured-Parzen-style sampler (the reference AutoML's hyperopt
    backend, simplified): after a random warmup, candidates are drawn around
    the good quantile of past trials and ranked by a Parzen density ratio
    l(x)/g(x); categorical axes use frequency-weighted draws."""

    def __init__(self, mode: str = "min", seed: int = 0, gamma: float = 0.25,
                 n_candidates: int = 24, n_warmup: int = 5):
        super().__init__(mode)
        self.rng = np.random.default_rng(seed)
        self.gamma = gamma
        self.n_candidates = n_candidates
        self.n_warmup = n_warmup

    # -- Parzen helpers (flat numeric/categorical spaces) -------------------
    def _split(self):
        sign = 1.0 if self.mode == "min" else -1.0
        done = [r for r in self.results if r.error is None]
        done.sort(key=lambda r: sign * r.metric)
        n_good = max(1, int(np.ceil(self.gamma * len(done))))
        return done[:n_good], done[n_good:]

    def _density(self, xs: List[float], x: float, scale: float) -> float:
        if not xs:
            return 1e-12
        xs = np.asarray(xs, np.float64)
        return float(np.mean(np.exp(-0.5 * ((x - xs) / scale) ** 2))
                     / (scale * np.sqrt(2 * np.pi)) + 1e-12)

    @staticmethod
    def _get_path(config, path):
        node = config
        for p in path:
            if not isinstance(node, dict) or p not in node:
                return None
            node = node[p]
        return node

    def _propose(self, space):
        good, bad = self._split()

        # precompute candidate-independent per-axis histories (flattened over
        # nested sub-spaces)
        axes = []  # (path, sampler)

        def walk(sp, path):
            for k, v in sp.items():
                if isinstance(v, dict):
                    walk(v, path + (k,))
                elif isinstance(v, hp_mod.Sampler):
                    axes.append((path + (k,), v))

        walk(space, ())

        # each numeric axis works in its NATURAL space: log for LogUniform
        # (perturbing/scoring log-scale params linearly pins proposals to the
        # clip boundaries), identity otherwise
        def to_t(v, x):
            return float(np.log(x)) if isinstance(v, hp_mod.LogUniform) \
                else float(x)

        def from_t(v, t):
            if isinstance(v, hp_mod.LogUniform):
                return float(np.exp(np.clip(t, v.lower, v.upper)))
            if isinstance(v, hp_mod.QUniform):
                # clamp into the sampling interval BEFORE rounding so the
                # result stays on the q-grid exactly like QUniform.sample
                return float(np.round(np.clip(t, v.lower, v.upper) / v.q)
                             * v.q)
            if isinstance(v, hp_mod.Uniform):
                return float(np.clip(t, v.lower, v.upper))
            if isinstance(v, hp_mod.RandInt):
                return int(np.clip(round(t), v.lower, v.upper - 1))
            return t

        def axis_width(v):
            if isinstance(v, (hp_mod.LogUniform, hp_mod.Uniform,
                              hp_mod.QUniform, hp_mod.RandInt)):
                return float(v.upper - v.lower)  # LogUniform bounds are logs
            return 1.0

        hist = {}
        for path, v in axes:
            gx = [self._get_path(r.config, path) for r in good]
            bx = [self._get_path(r.config, path) for r in bad]
            gx = [x for x in gx if x is not None]
            bx = [x for x in bx if x is not None]
            if isinstance(v, hp_mod.Choice):
                hist[path] = (gx, bx, None)
            else:
                gt = [to_t(v, x) for x in gx]
                bt = [to_t(v, x) for x in bx]
                vals = gt + bt
                scale = ((max(vals) - min(vals)) * 0.25 + 1e-9) if vals \
                    else axis_width(v) * 0.25
                hist[path] = (gt, bt, scale)

        def sample_axis(path, v):
            gt, _, _ = hist[path]
            if isinstance(v, hp_mod.Choice):
                opts = v.options
                counts = np.ones(len(opts))
                for x in gt:
                    if x in opts:
                        counts[opts.index(x)] += 1
                return opts[int(self.rng.choice(
                    len(opts), p=counts / counts.sum()))]
            if gt and self.rng.random() < 0.8:
                mu = gt[int(self.rng.integers(len(gt)))]
                t = self.rng.normal(mu, 0.1 * axis_width(v) + 1e-12)
                return from_t(v, t)
            return v.sample(self.rng)

        def build(sp, path):
            cfg = {}
            for k, v in sp.items():
                if isinstance(v, dict):
                    cfg[k] = build(v, path + (k,))
                elif isinstance(v, hp_mod.Sampler):
                    cfg[k] = sample_axis(path + (k,), v)
                else:
                    cfg[k] = v
            return cfg

        cands = [build(space, ()) for _ in range(self.n_candidates)]

        def score(cfg):
            s = 0.0
            for path, v in axes:
                if isinstance(v, hp_mod.Choice):
                    continue
                gt, bt, scale = hist[path]
                x = to_t(v, self._get_path(cfg, path))
                s += np.log(self._density(gt, x, scale))
                s -= np.log(self._density(bt, x, scale))
            return s

        return max(cands, key=score)

    def _configs(self, space, n_sampling):
        for i in range(n_sampling):
            if i < self.n_warmup or len(
                    [r for r in self.results if r.error is None]) < 2:
                yield hp_mod.sample_space(space, self.rng)
            else:
                yield self._propose(space)


def vmap_sweep(fn: Callable[[Dict[str, Any]], Any], space: Dict[str, Any],
               n_sampling: int = 8, mode: str = "min", seed: int = 0,
               mesh=None):
    """Gang-evaluate ``n_sampling`` configs inside ONE jitted vmap, sharded
    over the mesh's data axis — the XLA-native replacement for a Ray Tune
    actor pool when the trial is a pure jax function of its (numeric)
    hyperparameters with config-independent shapes.

    ``fn(config) -> scalar metric`` receives a config whose NUMERIC leaves
    are traced scalars (Choice axes are not supported — shapes/branches
    must not depend on the config).  Returns ``(best_config, best_metric,
    all_metrics)``; each device evaluates ``n_sampling / n_devices``
    configs in parallel.
    """
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    configs = [hp_mod.sample_space(space, rng) for _ in range(n_sampling)]

    # stack numeric leaves -> a pytree of (n,) arrays; non-numeric leaves
    # must be identical across configs (they become static closure values)
    paths: List[tuple] = []

    def walk(sp, path):
        for k, v in sp.items():
            if isinstance(v, dict):
                walk(v, path + (k,))
            elif isinstance(v, hp_mod.Sampler):
                if isinstance(v, hp_mod.Choice):
                    raise ValueError(
                        "vmap_sweep: Choice axes are not vmappable (shape/"
                        "branch-changing); use Searcher(parallel=...) for "
                        "those")
                paths.append(path + (k,))

    walk(space, ())

    def get(cfg, path):
        for p in path:
            cfg = cfg[p]
        return cfg

    def put(cfg, path, val):
        out = dict(cfg)
        node = out
        for p in path[:-1]:
            node[p] = dict(node[p])
            node = node[p]
        node[path[-1]] = val
        return out

    stacked = {path: jnp.asarray([get(c, path) for c in configs],
                                 jnp.float32) for path in paths}

    def one(leaf_vals):
        cfg = configs[0]
        for path, v in leaf_vals.items():
            cfg = put(cfg, path, v)
        return fn(cfg)

    gang = jax.jit(jax.vmap(one))
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        # shard the trial dimension over ALL mesh axes jointly (axis_names
        # [0] alone is the size-1 outer axis — dcn_data/pipe — which would
        # leave every trial on device 0)
        sharding = NamedSharding(mesh, P(tuple(mesh.axis_names)))
        if n_sampling % mesh.devices.size == 0:
            stacked = {k: jax.device_put(v, sharding)
                       for k, v in stacked.items()}
    metrics = np.asarray(jax.device_get(gang(stacked)), np.float64)
    metrics = np.where(np.isfinite(metrics), metrics,
                       np.inf if mode == "min" else -np.inf)
    best_i = int(np.argmin(metrics) if mode == "min" else np.argmax(metrics))
    return configs[best_i], float(metrics[best_i]), metrics
