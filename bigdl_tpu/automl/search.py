"""Trial searchers — reference ``orca/automl/search/`` (Ray-Tune-backed
SearchEngine; here in-process sequential trials, see package docstring)."""

import dataclasses
import time
import traceback
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from bigdl_tpu.automl import hp as hp_mod
from bigdl_tpu.utils.log import get_logger

log = get_logger(__name__)


@dataclasses.dataclass
class TrialResult:
    config: Dict[str, Any]
    metric: float
    artifacts: Any = None          # whatever the trial fn returned alongside
    duration_s: float = 0.0
    error: Optional[str] = None


class Searcher:
    """Drive trial_fn(config) -> (metric, artifacts) over a search space."""

    def __init__(self, mode: str = "min"):
        assert mode in ("min", "max")
        self.mode = mode
        self.results: List[TrialResult] = []

    def _configs(self, space, n_sampling):
        raise NotImplementedError

    def run(self, trial_fn: Callable[[Dict], Any], space: Dict[str, Any],
            n_sampling: int = 8) -> TrialResult:
        sign = 1.0 if self.mode == "min" else -1.0
        best = None
        for i, config in enumerate(self._configs(space, n_sampling)):
            t0 = time.perf_counter()
            try:
                out = trial_fn(config)
                metric, artifacts = out if isinstance(out, tuple) else (out,
                                                                        None)
                res = TrialResult(config, float(metric), artifacts,
                                  time.perf_counter() - t0)
            except Exception:  # noqa: BLE001 — a bad config must not kill the sweep
                res = TrialResult(config, float("inf") * sign, None,
                                  time.perf_counter() - t0,
                                  error=traceback.format_exc())
                log.warning("trial %d failed: %s", i, res.error.splitlines()[-1])
            self.results.append(res)
            if res.error is None and (
                    best is None or sign * res.metric < sign * best.metric):
                if best is not None:
                    best.artifacts = None  # only the winner's model is kept
                best = res
            else:
                res.artifacts = None
            log.info("trial %d/%s: metric=%s config=%s", i + 1,
                     n_sampling, res.metric, config)
        if best is None:
            raise RuntimeError("all trials failed; see results[*].error")
        return best


class RandomSearcher(Searcher):
    def __init__(self, mode: str = "min", seed: int = 0):
        super().__init__(mode)
        self.rng = np.random.default_rng(seed)

    def _configs(self, space, n_sampling):
        for _ in range(n_sampling):
            yield hp_mod.sample_space(space, self.rng)


class GridSearcher(Searcher):
    """Exhaustive over discrete axes; n_sampling caps the trial count."""

    def _configs(self, space, n_sampling):
        pts = hp_mod.grid_points(space)
        return pts[:n_sampling] if n_sampling else pts
