"""AutoEstimator — reference ``orca/automl/auto_estimator.py``:
``AutoEstimator.from_torch(model_creator, optimizer_creator, loss_creator)``
then ``.fit(data, search_space=…, n_sampling=…)`` → ``get_best_model()``.

TPU-native: creators take a concrete sampled ``config`` dict and the
trials train through the Orca-equivalent ``Estimator`` on the local
mesh (see ``bigdl_tpu/automl/__init__`` for why trials are sequential).
"""

from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from bigdl_tpu.automl.search import RandomSearcher, Searcher, TrialResult
from bigdl_tpu.estimator.estimator import Estimator


class AutoEstimator:
    def __init__(self, model_creator: Callable[[Dict], Any],
                 optimizer_creator: Callable[[Dict], Any],
                 loss_creator: Callable[[Dict], Any],
                 metric: str = "loss", mode: str = "min"):
        self.model_creator = model_creator
        self.optimizer_creator = optimizer_creator
        self.loss_creator = loss_creator
        self.metric = metric
        self.mode = mode
        self.best_result: Optional[TrialResult] = None
        self.best_estimator: Optional[Estimator] = None

    from_module = staticmethod(lambda *a, **k: AutoEstimator(*a, **k))

    def fit(self, data, validation_data=None, *, search_space: Dict[str, Any],
            n_sampling: int = 8, epochs: int = 1, batch_size: Any = 32,
            searcher: Optional[Searcher] = None,
            parallel=None) -> "AutoEstimator":
        """data: (x, y) arrays or anything Estimator.fit accepts.  The
        sampled config may carry 'batch_size'/'epochs' overrides."""
        searcher = searcher or RandomSearcher(mode=self.mode)
        val = validation_data if validation_data is not None else data

        from bigdl_tpu.optim import validation as V

        method_table = {"loss": lambda est: V.Loss(est.criterion),
                        "mse": lambda est: V.MSE(),
                        "mae": lambda est: V.MAE(),
                        "top1accuracy": lambda est: V.Top1Accuracy(),
                        "accuracy": lambda est: V.Top1Accuracy()}
        make_method = method_table.get(self.metric.lower())
        if make_method is None:
            raise ValueError(f"unknown metric {self.metric!r}; "
                             f"one of {sorted(method_table)}")

        def trial(config):
            est = Estimator.from_module(
                self.model_creator, self.optimizer_creator,
                self.loss_creator, config=config)
            est.fit(data, epochs=int(config.get("epochs", epochs)),
                    batch_size=int(config.get("batch_size", batch_size)))
            stats = est.evaluate(val, [make_method(est)])
            return float(list(stats.values())[0]), est

        self.best_result = searcher.run(trial, search_space, n_sampling,
                                        parallel=parallel)
        self.best_estimator = self.best_result.artifacts
        self.searcher = searcher
        return self

    def get_best_model(self):
        self._check()
        return self.best_estimator.get_model()

    def get_best_config(self) -> Dict[str, Any]:
        self._check()
        return self.best_result.config

    def _check(self):
        if self.best_result is None:
            raise RuntimeError("call fit() first")
