"""Search-space DSL — reference ``orca/automl/hp.py`` (``hp.choice``,
``hp.uniform``, ``hp.randint``, … thin wrappers over Ray Tune sample
spaces; here self-contained samplers)."""

import math
from typing import Any, Dict, List, Sequence

import numpy as np


class Sampler:
    def sample(self, rng: np.random.Generator):
        raise NotImplementedError

    def grid(self) -> List[Any]:
        """Discrete support for grid search (None = continuous)."""
        return None


class Choice(Sampler):
    def __init__(self, options: Sequence[Any]):
        self.options = list(options)

    def sample(self, rng):
        return self.options[int(rng.integers(0, len(self.options)))]

    def grid(self):
        return list(self.options)


class Uniform(Sampler):
    def __init__(self, lower: float, upper: float):
        self.lower, self.upper = float(lower), float(upper)

    def sample(self, rng):
        return float(rng.uniform(self.lower, self.upper))


class QUniform(Sampler):
    def __init__(self, lower: float, upper: float, q: float):
        self.lower, self.upper, self.q = float(lower), float(upper), float(q)

    def sample(self, rng):
        v = rng.uniform(self.lower, self.upper)
        return float(np.round(v / self.q) * self.q)


class LogUniform(Sampler):
    def __init__(self, lower: float, upper: float):
        self.lower, self.upper = math.log(lower), math.log(upper)

    def sample(self, rng):
        return float(math.exp(rng.uniform(self.lower, self.upper)))


class RandInt(Sampler):
    def __init__(self, lower: int, upper: int):
        self.lower, self.upper = int(lower), int(upper)

    def sample(self, rng):
        return int(rng.integers(self.lower, self.upper))


def choice(options):
    return Choice(options)


def uniform(lower, upper):
    return Uniform(lower, upper)


def quniform(lower, upper, q):
    return QUniform(lower, upper, q)


def loguniform(lower, upper):
    return LogUniform(lower, upper)


def randint(lower, upper):
    return RandInt(lower, upper)


def sample_space(space: Dict[str, Any], rng: np.random.Generator
                 ) -> Dict[str, Any]:
    """Resolve a (possibly nested) search space into a concrete config."""
    out = {}
    for k, v in space.items():
        if isinstance(v, Sampler):
            out[k] = v.sample(rng)
        elif isinstance(v, dict):
            out[k] = sample_space(v, rng)
        else:
            out[k] = v
    return out


def grid_points(space: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Cartesian product of all discrete axes (continuous axes forbidden)."""
    keys, axes = [], []
    fixed = {}
    for k, v in space.items():
        if isinstance(v, Sampler):
            g = v.grid()
            if g is None:
                raise ValueError(
                    f"grid search needs discrete axes; '{k}' is continuous")
            keys.append(k)
            axes.append(g)
        elif isinstance(v, dict):
            sub = grid_points(v)
            keys.append(k)
            axes.append(sub)
        else:
            fixed[k] = v
    points = [dict(fixed)]
    for k, axis in zip(keys, axes):
        points = [dict(p, **{k: a}) for p in points for a in axis]
    return points
