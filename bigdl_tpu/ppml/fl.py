"""Horizontal federated learning — the PPML FLServer/FLClient analog.

Reference analog (unverified — mount empty): ``scala/ppml/.../FLServer.scala``
/ ``FLClient.scala`` — a gRPC server aggregating client updates (FedAvg for
NN), clients train locally and sync per round.

TPU-native re-design: the transport is plain HTTP on the trusted cluster
network (the reference's gRPC role; SGX enclaves are hardware-specific and
out of scope — SURVEY.md §3.2).  Model updates travel as npz-serialized
pytrees.  Aggregation is weighted FedAvg; the server releases a round's
global model only after all ``world_size`` clients have submitted, mirroring
the reference's synchronous round barrier."""

import io
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional
from urllib import request as urlrequest

import numpy as np

# single pytree<->flat implementation shared with the checkpoint format
from bigdl_tpu.utils.serializer import _flatten, _unflatten_like


def _flat_to_npz_bytes(flat: Dict[str, np.ndarray]) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, **{k.replace("/", "⁄"): v for k, v in flat.items()})
    return buf.getvalue()


def _tree_to_npz_bytes(tree) -> bytes:
    return _flat_to_npz_bytes(_flatten(tree))


def _npz_bytes_to_flat(data: bytes) -> Dict[str, np.ndarray]:
    with np.load(io.BytesIO(data)) as z:
        return {k.replace("⁄", "/"): z[k] for k in z.files}


class FedAvg:
    """Weighted-average aggregator: sum(w_i · update_i) / sum(w_i)."""

    def __init__(self):
        self._acc: Optional[Dict[str, np.ndarray]] = None
        self._weight = 0.0

    def add(self, flat: Dict[str, np.ndarray], weight: float) -> None:
        if self._acc is None:
            self._acc = {k: v.astype(np.float64) * weight
                         for k, v in flat.items()}
        else:
            for k, v in flat.items():
                self._acc[k] = self._acc[k] + v.astype(np.float64) * weight
        self._weight += weight

    def result(self) -> Dict[str, np.ndarray]:
        if self._acc is None:
            raise RuntimeError("no updates to aggregate")
        # keys containing "@sum" aggregate as plain weighted SUMS
        # (histogram exchange for FGBoost); everything else is the weighted
        # average.  Substring match: client-side pytree flattening decorates
        # keys (e.g. "['lo@sum']"), so suffix tests would never fire.
        return {k: (v if "@sum" in k else v / self._weight)
                .astype(np.float32)
                for k, v in self._acc.items()}


class _FLState:
    def __init__(self, world_size: int):
        self.world_size = world_size
        self.lock = threading.Condition()
        self.round = 0
        self.agg = FedAvg()
        self.submitted: set = set()
        self.global_flat: Optional[Dict[str, np.ndarray]] = None
        self.psi_sets: Dict[str, list] = {}
        self.psi_salt: Optional[str] = None


class _Handler(BaseHTTPRequestHandler):
    state: _FLState  # injected by FLServer

    def log_message(self, *args):  # quiet
        pass

    def _send(self, code: int, body: bytes,
              ctype: str = "application/octet-stream"):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _read_body(self) -> bytes:
        n = int(self.headers.get("Content-Length", 0))
        return self.rfile.read(n)

    def do_GET(self):
        st = self.state
        if self.path.startswith("/model"):
            # /model?round=R — block (long-poll) until round R aggregated
            want = int(self.path.split("round=")[1])
            with st.lock:
                ok = st.lock.wait_for(
                    lambda: st.round >= want and st.global_flat is not None,
                    timeout=60.0)
                if not ok:
                    self._send(408, b"round not complete")
                    return
                if st.round != want:
                    # never serve round R+k weights labeled as round R
                    self._send(409, f"server at round {st.round}, "
                               f"wanted {want}".encode())
                    return
                body = _flat_to_npz_bytes(st.global_flat)
            self._send(200, body)
        elif self.path == "/status":
            with st.lock:
                body = json.dumps({
                    "round": st.round,
                    "submitted": len(st.submitted),
                    "world_size": st.world_size}).encode()
            self._send(200, body, "application/json")
        else:
            self._send(404, b"")

    def do_POST(self):
        st = self.state
        if self.path.startswith("/update"):
            # /update?client=ID&weight=W&round=R
            q = dict(p.split("=") for p in self.path.split("?")[1].split("&"))
            flat = _npz_bytes_to_flat(self._read_body())
            with st.lock:
                if int(q["round"]) != st.round:
                    self._send(409, f"server at round {st.round}".encode())
                    return
                if q["client"] in st.submitted:
                    self._send(409, b"duplicate submission")
                    return
                st.submitted.add(q["client"])
                st.agg.add(flat, float(q.get("weight", 1.0)))
                if len(st.submitted) == st.world_size:
                    st.global_flat = st.agg.result()
                    st.round += 1
                    st.agg = FedAvg()
                    st.submitted = set()
                    st.lock.notify_all()
            self._send(200, b"ok")
        elif self.path.startswith("/psi"):
            from bigdl_tpu.ppml.psi import handle_psi_post

            handle_psi_post(self, st)
        else:
            self._send(404, b"")


class FLServer:
    """Synchronous-round FedAvg server.  ``with FLServer(world_size=2) as s:``

    TLS (reference ``scala/grpc`` TLS builders): pass ``tls_cert``/
    ``tls_key`` (see ``ppml.tls.generate_self_signed``) and the transport
    becomes https; clients pin the same cert via ``FLClient(cafile=...)``."""

    def __init__(self, world_size: int, host: str = "127.0.0.1",
                 port: int = 0, tls_cert: Optional[str] = None,
                 tls_key: Optional[str] = None):
        self.state = _FLState(world_size)
        handler = type("BoundHandler", (_Handler,), {"state": self.state})
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.tls = tls_cert is not None
        if self.tls:
            from bigdl_tpu.ppml.tls import server_context

            self.httpd.socket = server_context(tls_cert, tls_key).wrap_socket(
                self.httpd.socket, server_side=True)
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    @property
    def target(self) -> str:
        return f"http{'s' if self.tls else ''}://127.0.0.1:{self.port}"

    def start(self) -> "FLServer":
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


def _http(url: str, data: bytes = None, method: str = "GET",
          timeout: float = 70.0, ctx=None):
    """(status, body) — urllib raises HTTPError on non-2xx; normalize it so
    callers can branch on status codes."""
    from urllib.error import HTTPError

    req = urlrequest.Request(url, data=data, method=method)
    try:
        with urlrequest.urlopen(req, timeout=timeout, context=ctx) as r:
            return r.status, r.read()
    except HTTPError as e:
        return e.code, e.read()


class FLClient:
    """One federated party: local train steps + round sync."""

    def __init__(self, target: str, client_id: str,
                 cafile: Optional[str] = None):
        self.target = target
        self.client_id = client_id
        self.round = 0
        self._ctx = None
        if cafile is not None:
            from bigdl_tpu.ppml.tls import client_context

            self._ctx = client_context(cafile)

    def upload(self, variables: Any, weight: float = 1.0) -> None:
        body = _tree_to_npz_bytes(variables)
        url = (f"{self.target}/update?client={self.client_id}"
               f"&weight={weight}&round={self.round}")
        code, resp = _http(url, data=body, method="POST", ctx=self._ctx)
        if code != 200:
            raise RuntimeError(
                f"upload for round {self.round} failed ({code}): "
                f"{resp[:200].decode(errors='replace')}")

    def download(self, template: Any, max_wait: float = 300.0) -> Any:
        """Blocks until the current round's aggregate is ready, then returns
        the global model shaped like ``template``.  Retries long-poll
        timeouts (408) until ``max_wait``; a 409 means this client fell a
        whole round behind and must re-join (fatal here)."""
        want = self.round + 1
        url = f"{self.target}/model?round={want}"
        deadline = time.monotonic() + max_wait
        while True:
            code, body = _http(url, ctx=self._ctx)
            if code == 200:
                break
            if code == 408 and time.monotonic() < deadline:
                continue  # peers still training — keep long-polling
            raise RuntimeError(
                f"download of round {want} failed ({code}): "
                f"{body[:200].decode(errors='replace')}")
        self.round = want
        return _unflatten_like(template, _npz_bytes_to_flat(body))

    def sync(self, variables: Any, weight: float = 1.0) -> Any:
        """upload + download — one federated round."""
        self.upload(variables, weight)
        return self.download(variables)

    def status(self) -> Dict[str, Any]:
        with urlrequest.urlopen(f"{self.target}/status", timeout=10,
                                context=self._ctx) as r:
            return json.loads(r.read())
