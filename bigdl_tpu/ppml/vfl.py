"""Vertical federated learning (split NN) — the PPML VFL-NN aggregator analog.

Reference analog (unverified — mount empty): ``scala/ppml/.../fl/nn/`` — the
VFL aggregator: each party owns a feature slice and a bottom model; parties
send bottom-model activations to the aggregator, which runs the top model +
loss, and returns per-party activation gradients; each party backprops its
bottom model locally.  Labels live only at the aggregator (or one party).

TPU-native: each party's bottom step and the aggregator's top step are
separately jitted; the exchanged tensors (activations / activation grads) are
the only cross-party traffic, exactly as in the reference.  Transport here is
in-process (the HTTP hop of fl.py can carry the npz payloads identically);
the privacy boundary — raw features and bottom weights never leave a party —
is preserved by construction."""

from typing import Any, Callable, Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp


class _Party:
    def __init__(self, name: str, model, variables, optimizer):
        self.name = name
        self.model = model
        self.variables = variables
        self.opt = optimizer
        self.opt_state = optimizer.init_state(variables["params"])


class VFLNNTrainer:
    """Aggregator + parties, synchronous per-batch protocol:

    1. each party p: ``a_p = bottom_p(x_p)``           (activation upload)
    2. aggregator:  ``loss = criterion(top(concat(a)), y)``;
       grads for top params AND each ``∂loss/∂a_p``    (grad download)
    3. each party p: VJP of its bottom model with ``∂loss/∂a_p``; local
       optimizer step.  Raw ``x_p`` and bottom params never move.
    """

    def __init__(self, top_model, top_variables, criterion, optimizer_factory):
        self.top = _Party("top", top_model, top_variables,
                          optimizer_factory())
        self.criterion = criterion
        self.optimizer_factory = optimizer_factory
        self.parties: List[_Party] = []
        self._step = 0

    def add_party(self, name: str, model, variables) -> None:
        self.parties.append(
            _Party(name, model, variables, self.optimizer_factory()))

    # ---- party side -------------------------------------------------------
    def _bottom_forward(self, party: _Party, x):
        def fwd(params):
            y, _ = party.model.forward(params, party.variables.get(
                "state", {}), x, training=True)
            return y

        return jax.vjp(fwd, party.variables["params"])

    # ---- aggregator side --------------------------------------------------
    def _top_step(self, acts: Sequence[jnp.ndarray], y):
        def loss_fn(top_params, acts):
            joined = jnp.concatenate(list(acts), axis=-1)
            out, _ = self.top.model.forward(
                top_params, self.top.variables.get("state", {}), joined,
                training=True)
            return self.criterion(out, y)

        loss, (g_top, g_acts) = jax.value_and_grad(
            loss_fn, argnums=(0, 1))(self.top.variables["params"],
                                     tuple(acts))
        return loss, g_top, g_acts

    # ---- protocol ---------------------------------------------------------
    def train_batch(self, xs: Dict[str, Any], y) -> float:
        """One synchronous VFL round over per-party feature slices ``xs``."""
        acts, vjps = [], []
        for p in self.parties:
            a, vjp = self._bottom_forward(p, xs[p.name])
            acts.append(a)
            vjps.append(vjp)

        loss, g_top, g_acts = self._top_step(acts, y)

        new_top, self.top.opt_state = self.top.opt.update(
            self._step, g_top, self.top.variables["params"],
            self.top.opt_state)
        self.top.variables = dict(self.top.variables, params=new_top)

        for p, vjp, g_a in zip(self.parties, vjps, g_acts):
            (g_bottom,) = vjp(g_a)
            new_p, p.opt_state = p.opt.update(
                self._step, g_bottom, p.variables["params"], p.opt_state)
            p.variables = dict(p.variables, params=new_p)

        self._step += 1
        return float(loss)

    def predict(self, xs: Dict[str, Any]):
        acts = []
        for p in self.parties:
            a, _ = p.model.forward(p.variables["params"],
                                   p.variables.get("state", {}), xs[p.name])
            acts.append(a)
        out, _ = self.top.model.forward(
            self.top.variables["params"], self.top.variables.get("state", {}),
            jnp.concatenate(acts, axis=-1))
        return out
