"""TLS for the PPML control plane.

Reference analog (unverified — mount empty): ``scala/grpc`` — the shared
gRPC plumbing ships TLS service builders used by the FL server/clients.
PPML is the one subsystem whose point is NOT trusting the network, so the
HTTP transport here gets the same option: a self-signed server certificate
(generated in-process) and a client context pinned to that certificate
(private-CA trust, no hostname dance beyond the CN/SAN)."""

import datetime
import ipaddress
import os
import ssl
from typing import Tuple


def generate_self_signed(out_dir: str, common_name: str = "bigdl-tpu-fl",
                         days: int = 365) -> Tuple[str, str]:
    """Write a self-signed cert + key pair; returns (cert_path, key_path).

    The cert carries SANs for localhost/127.0.0.1 plus ``common_name`` so
    pinned clients verify cleanly on the loopback and cluster DNS names."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name(
        [x509.NameAttribute(NameOID.COMMON_NAME, common_name)])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name).issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=days))
        .add_extension(x509.SubjectAlternativeName([
            x509.DNSName("localhost"),
            x509.DNSName(common_name),
            x509.IPAddress(ipaddress.ip_address("127.0.0.1")),
        ]), critical=False)
        .sign(key, hashes.SHA256())
    )
    os.makedirs(out_dir, exist_ok=True)
    cert_path = os.path.join(out_dir, "server.crt")
    key_path = os.path.join(out_dir, "server.key")
    with open(cert_path, "wb") as f:
        f.write(cert.public_bytes(serialization.Encoding.PEM))
    with open(key_path, "wb") as f:
        f.write(key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.TraditionalOpenSSL,
            serialization.NoEncryption()))
    return cert_path, key_path


def server_context(cert_path: str, key_path: str) -> ssl.SSLContext:
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cert_path, key_path)
    return ctx


def client_context(cafile: str) -> ssl.SSLContext:
    """Trust exactly the given (self-signed) certificate — private-CA
    pinning, NOT certificate-check disabling."""
    ctx = ssl.create_default_context(cafile=cafile)
    ctx.check_hostname = False  # pinned trust; CN varies across clusters
    ctx.verify_mode = ssl.CERT_REQUIRED
    return ctx
