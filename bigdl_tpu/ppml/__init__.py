from bigdl_tpu.ppml.fl import FLServer, FLClient, FedAvg
from bigdl_tpu.ppml.psi import PSIServer, psi_intersect, salted_hashes
from bigdl_tpu.ppml.vfl import VFLNNTrainer
from bigdl_tpu.ppml.fgboost import FGBoostClassifier, FGBoostRegression

__all__ = ["FLServer", "FLClient", "FedAvg", "PSIServer", "psi_intersect",
           "salted_hashes", "VFLNNTrainer", "FGBoostRegression",
           "FGBoostClassifier"]
