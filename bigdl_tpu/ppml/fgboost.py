"""FGBoost — federated gradient-boosted trees.

Reference analog (unverified — mount empty): ``scala/ppml/.../fl/fgboost``
(SURVEY.md §3.4 PPML FL: "FGBoost (federated gbt)") — horizontally-
federated XGBoost-style regression/classification: parties hold disjoint
sample sets, exchange per-bin gradient/hessian histograms through the FL
server, and every party derives the SAME tree from the aggregated
histograms (the server is a dumb aggregator; no raw samples ever leave a
party).

Design: second-order boosting (gain = G²/(H+λ) on histogram prefix sums),
level-wise growth to ``max_depth``, trees stored as flat arrays so predict
is a vectorized gather loop (TPU/XLA-friendly; no per-sample recursion).
Single-party operation (``fl_client=None``) is plain local GBT — the same
code path minus the sync.
"""

from typing import Dict, List, Optional, Tuple

import numpy as np


class _Tree:
    """Flat-array binary tree (complete, level-wise, depth d)."""

    __slots__ = ("feature", "threshold", "leaf_value", "is_leaf")

    def __init__(self, n_nodes: int):
        self.feature = np.zeros(n_nodes, np.int32)
        self.threshold = np.zeros(n_nodes, np.float32)
        self.leaf_value = np.zeros(n_nodes, np.float32)
        self.is_leaf = np.ones(n_nodes, bool)

    def predict(self, x: np.ndarray) -> np.ndarray:
        node = np.zeros(len(x), np.int64)
        depth = int(np.log2(len(self.feature) + 1))
        for _ in range(depth - 1):
            leaf = self.is_leaf[node]
            # <= matches the histogram binning (side='left' searchsorted):
            # a sample equal to the edge goes LEFT in training too
            go_left = x[np.arange(len(x)), self.feature[node]] \
                <= self.threshold[node]
            child = np.where(go_left, 2 * node + 1, 2 * node + 2)
            node = np.where(leaf, node, child)
        return self.leaf_value[node]


class FGBoostRegression:
    """Federated (or local) gradient-boosted regression trees.

    ``fit(x, y, fl_client=...)``: with an ``FLClient`` every histogram
    round syncs through the FL server; all parties finish with identical
    models.  Objective: squared error (``objective="squared"``) or
    logistic (``objective="logistic"`` — use ``predict_proba``)."""

    def __init__(self, n_trees: int = 20, max_depth: int = 4,
                 learning_rate: float = 0.1, n_bins: int = 32,
                 reg_lambda: float = 1.0, gamma: float = 0.0,
                 min_child_weight: float = 1e-3,
                 objective: str = "squared"):
        if objective not in ("squared", "logistic"):
            raise ValueError("objective: squared | logistic")
        self.n_trees = n_trees
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.n_bins = n_bins
        self.reg_lambda = reg_lambda
        self.gamma = gamma
        self.min_child_weight = min_child_weight
        self.objective = objective
        self.trees: List[_Tree] = []
        self.base_score = 0.0
        self.bin_edges: Optional[np.ndarray] = None  # (F, n_bins-1)

    # ------------------------------------------------------------------
    def _grad_hess(self, y, pred) -> Tuple[np.ndarray, np.ndarray]:
        if self.objective == "squared":
            return pred - y, np.ones_like(y)
        p = 1.0 / (1.0 + np.exp(-pred))
        return p - y, np.maximum(p * (1 - p), 1e-6)

    def _sync(self, fl_client, flat: Dict[str, np.ndarray]
              ) -> Dict[str, np.ndarray]:
        if fl_client is None:
            return flat
        tagged = {k + "@sum": v.astype(np.float32) for k, v in flat.items()}
        out = fl_client.sync(tagged, weight=1.0)
        return {k[:-len("@sum")]: np.asarray(v, np.float64)
                for k, v in out.items()}

    def _make_bins(self, x, fl_client):
        # shared bin edges from the GLOBAL feature range (min/max exchanged
        # as -max trick so a sum-free aggregate isn't needed: parties send
        # hist of per-feature min/-min maxima via sum of one-hot... keep it
        # simple: aggregate means of local min/max — adequate bin cover is
        # then guaranteed by clipping into the edge bins)
        lo = x.min(axis=0)
        hi = x.max(axis=0)
        agg = self._sync(fl_client, {"lo": lo, "hi": hi})
        if fl_client is not None:
            # sums of local mins/maxs; recover averages via the party count
            n = self._sync(fl_client, {"n": np.ones(1)})["n"][0]
            lo, hi = agg["lo"] / n, agg["hi"] / n
        span = np.maximum(hi - lo, 1e-12)
        qs = np.linspace(0, 1, self.n_bins + 1)[1:-1]
        self.bin_edges = (lo[:, None] + span[:, None] * qs[None, :]).astype(
            np.float32)

    def _binned(self, x) -> np.ndarray:
        out = np.empty(x.shape, np.int32)
        for f in range(x.shape[1]):
            out[:, f] = np.searchsorted(self.bin_edges[f], x[:, f])
        return out  # values in [0, n_bins-1]

    # ------------------------------------------------------------------
    def fit(self, x, y, fl_client=None) -> "FGBoostRegression":
        x = np.asarray(x, np.float32)
        y = np.asarray(y, np.float32).reshape(-1)
        n, n_feat = x.shape
        self._make_bins(x, fl_client)
        binned = self._binned(x)

        base = self._sync(fl_client, {"ysum": np.array([y.sum()]),
                                      "cnt": np.array([float(n)])})
        mean_y = float(base["ysum"][0] / base["cnt"][0])
        self.base_score = (mean_y if self.objective == "squared"
                           else float(np.log(np.clip(mean_y, 1e-6, 1 - 1e-6)
                                             / (1 - np.clip(mean_y, 1e-6,
                                                            1 - 1e-6)))))
        pred = np.full(n, self.base_score, np.float32)
        self.trees = []

        n_nodes = 2 ** self.max_depth - 1

        for _ in range(self.n_trees):
            g, h = self._grad_hess(y, pred)
            tree = _Tree(n_nodes)
            node_of = np.zeros(n, np.int64)  # current node per sample
            # per-node G/H totals for leaf values + gain baseline
            for level in range(self.max_depth - 1):
                lo_n, hi_n = 2 ** level - 1, 2 ** (level + 1) - 1
                frontier = range(lo_n, hi_n)
                # histograms for every frontier node in one flat dict
                hists = {}
                for node in frontier:
                    mask = node_of == node
                    gb = binned[mask]
                    gw, hw = g[mask], h[mask]
                    hg = np.zeros((n_feat, self.n_bins))
                    hh = np.zeros((n_feat, self.n_bins))
                    for f in range(n_feat):
                        hg[f] = np.bincount(gb[:, f], weights=gw,
                                            minlength=self.n_bins)
                        hh[f] = np.bincount(gb[:, f], weights=hw,
                                            minlength=self.n_bins)
                    hists[f"n{node}/g"] = hg
                    hists[f"n{node}/h"] = hh
                hists = self._sync(fl_client, hists)

                for node in frontier:
                    hg, hh = hists[f"n{node}/g"], hists[f"n{node}/h"]
                    G = hg.sum(axis=1)[0:1].sum()  # same for every feature
                    H = hh.sum(axis=1)[0:1].sum()
                    if H < self.min_child_weight:
                        continue  # stays a leaf
                    gl = np.cumsum(hg, axis=1)[:, :-1]   # (F, bins-1)
                    hl = np.cumsum(hh, axis=1)[:, :-1]
                    gr, hr = G - gl, H - hl
                    lam = self.reg_lambda
                    gain = (gl ** 2 / (hl + lam) + gr ** 2 / (hr + lam)
                            - G ** 2 / (H + lam)) / 2 - self.gamma
                    ok = (hl >= self.min_child_weight) & \
                         (hr >= self.min_child_weight)
                    gain = np.where(ok, gain, -np.inf)
                    f_best, b_best = np.unravel_index(np.argmax(gain),
                                                      gain.shape)
                    if not np.isfinite(gain[f_best, b_best]) or \
                            gain[f_best, b_best] <= 0:
                        continue
                    tree.is_leaf[node] = False
                    tree.feature[node] = f_best
                    tree.threshold[node] = self.bin_edges[f_best, b_best]
                    mask = node_of == node
                    go_left = binned[mask, f_best] <= b_best
                    children = np.where(go_left, 2 * node + 1, 2 * node + 2)
                    node_of[mask] = children

            # leaf values from aggregated G/H of terminal nodes
            leaf_stats = {}
            for node in range(n_nodes):
                mask = node_of == node
                leaf_stats[f"l{node}"] = np.array(
                    [g[mask].sum(), h[mask].sum()])
            leaf_stats = self._sync(fl_client, leaf_stats)
            for node in range(n_nodes):
                G, H = leaf_stats[f"l{node}"]
                tree.leaf_value[node] = (-G / (H + self.reg_lambda)
                                         * self.learning_rate
                                         if H > 0 else 0.0)
            self.trees.append(tree)
            pred = pred + tree.predict(x)
        return self

    # ------------------------------------------------------------------
    def predict(self, x) -> np.ndarray:
        x = np.asarray(x, np.float32)
        out = np.full(len(x), self.base_score, np.float32)
        for t in self.trees:
            out += t.predict(x)
        return out

    def predict_proba(self, x) -> np.ndarray:
        return 1.0 / (1.0 + np.exp(-self.predict(x)))

    # ------------------------------------------------------------------
    def save(self, path: str) -> None:
        blobs = {"base_score": np.array([self.base_score]),
                 "bin_edges": self.bin_edges,
                 "objective": np.frombuffer(
                     self.objective.encode(), np.uint8)}
        for i, t in enumerate(self.trees):
            blobs[f"t{i}/feature"] = t.feature
            blobs[f"t{i}/threshold"] = t.threshold
            blobs[f"t{i}/leaf_value"] = t.leaf_value
            blobs[f"t{i}/is_leaf"] = t.is_leaf
        np.savez(path, **blobs)

    @staticmethod
    def load(path: str) -> "FGBoostRegression":
        data = np.load(path if path.endswith(".npz") else path + ".npz")
        model = FGBoostRegression()
        model.base_score = float(data["base_score"][0])
        model.bin_edges = data["bin_edges"]
        model.objective = bytes(data["objective"]).decode()
        i = 0
        while f"t{i}/feature" in data:
            t = _Tree(len(data[f"t{i}/feature"]))
            t.feature = data[f"t{i}/feature"]
            t.threshold = data[f"t{i}/threshold"]
            t.leaf_value = data[f"t{i}/leaf_value"]
            t.is_leaf = data[f"t{i}/is_leaf"]
            model.trees.append(t)
            i += 1
        return model


class FGBoostClassifier(FGBoostRegression):
    """Binary classifier: logistic objective + 0.5 threshold."""

    def __init__(self, **kw):
        kw.setdefault("objective", "logistic")
        super().__init__(**kw)

    def predict_class(self, x) -> np.ndarray:
        return (self.predict_proba(x) >= 0.5).astype(np.int32)
