"""Private set intersection — the PPML PSI analog.

Reference analog (unverified — mount empty): ``scala/ppml/.../psi/`` — the
FL server offers a PSI service: parties upload salted hashes of their record
ids; the server returns the intersection so vertically-partitioned parties
can align rows without revealing non-shared ids.

Protocol here (salted-hash PSI, the reference's scheme class): the server
issues one random salt per session; each party uploads
``sha256(salt || id)`` digests; the server intersects digests and returns
the matching digests to each party, which maps them back to its own ids
locally.  Ids never leave a party in the clear; non-intersecting ids are
only ever seen as salted hashes."""

import hashlib
import json
import secrets
from typing import Dict, List, Sequence
from urllib import request as urlrequest


def salted_hashes(ids: Sequence[str], salt: str) -> List[str]:
    return [hashlib.sha256((salt + str(i)).encode()).hexdigest()
            for i in ids]


def psi_intersect(ids_a: Sequence[str], ids_b: Sequence[str],
                  salt: str = None) -> List[str]:
    """In-process PSI (both sides local — test/reference path): returns the
    ids of party A that are shared with party B."""
    salt = salt or secrets.token_hex(16)
    ha = salted_hashes(ids_a, salt)
    hb = set(salted_hashes(ids_b, salt))
    return [i for i, h in zip(ids_a, ha) if h in hb]


# ---- HTTP service half (mounted on the FLServer) ---------------------------


def handle_psi_post(handler, state) -> None:
    """POST /psi/salt → {"salt": ...} (one fresh salt per server session,
    stored on the server state);
    POST /psi/upload?client=ID body={"hashes": [...]} → stores;
    POST /psi/intersect → intersection once ALL world_size parties have
    uploaded, else 409 (the same participation barrier /update enforces —
    intersecting early would silently return a too-large set)."""
    if handler.path.startswith("/psi/salt"):
        with state.lock:
            if state.psi_salt is None:
                state.psi_salt = secrets.token_hex(16)
            body = json.dumps({"salt": state.psi_salt}).encode()
        handler._send(200, body, "application/json")
    elif handler.path.startswith("/psi/upload"):
        q = dict(p.split("=") for p in handler.path.split("?")[1].split("&"))
        payload = json.loads(handler._read_body())
        with state.lock:
            state.psi_sets[q["client"]] = payload["hashes"]
        handler._send(200, b"ok")
    elif handler.path.startswith("/psi/intersect"):
        with state.lock:
            if len(state.psi_sets) < state.world_size:
                handler._send(
                    409, (f"only {len(state.psi_sets)}/{state.world_size} "
                          "parties uploaded").encode())
                return
            sets = [set(v) for v in state.psi_sets.values()]
            inter = set.intersection(*sets) if sets else set()
            body = json.dumps({"hashes": sorted(inter)}).encode()
        handler._send(200, body, "application/json")
    else:
        handler._send(404, b"")


class PSIServer:
    """Client-side helper speaking the /psi endpoints of an FLServer."""

    def __init__(self, target: str, client_id: str, cafile=None):
        self.target = target
        self.client_id = client_id
        self._salt = None
        self._ctx = None
        if cafile is not None:
            from bigdl_tpu.ppml.tls import client_context

            self._ctx = client_context(cafile)

    def get_salt(self) -> str:
        if self._salt is None:
            req = urlrequest.Request(f"{self.target}/psi/salt", data=b"",
                                     method="POST")
            with urlrequest.urlopen(req, timeout=10,
                                    context=self._ctx) as r:
                self._salt = json.loads(r.read())["salt"]
        return self._salt

    def upload_set(self, ids: Sequence[str]) -> None:
        salt = self.get_salt()
        body = json.dumps(
            {"hashes": salted_hashes(ids, salt)}).encode()
        req = urlrequest.Request(
            f"{self.target}/psi/upload?client={self.client_id}", data=body,
            method="POST")
        with urlrequest.urlopen(req, timeout=10, context=self._ctx) as r:
            assert r.status == 200

    def download_intersection(self, ids: Sequence[str],
                              max_wait: float = 60.0) -> List[str]:
        """Returns this party's ids that are in the global intersection.
        Polls until all parties have uploaded (409 until then)."""
        import time

        from bigdl_tpu.ppml.fl import _http

        salt = self.get_salt()
        deadline = time.monotonic() + max_wait
        while True:
            code, body = _http(f"{self.target}/psi/intersect", data=b"",
                               method="POST", timeout=10, ctx=self._ctx)
            if code == 200:
                inter = set(json.loads(body)["hashes"])
                break
            if code == 409 and time.monotonic() < deadline:
                time.sleep(0.05)
                continue
            raise RuntimeError(
                f"PSI intersect failed ({code}): "
                f"{body[:200].decode(errors='replace')}")
        return [i for i, h in zip(ids, salted_hashes(ids, salt))
                if h in inter]
