"""Pod-scale coordinated fault tolerance — the cluster control plane.

Reference analog (unverified — mount empty): the reference's headline
robustness property — transparent failure recovery — is inherited from the
Spark control plane ("BigDL 2.0", arXiv 2204.01715): the driver notices a
dead executor and reschedules.  A TPU multi-controller job has no driver,
and worse: one host dying does not fail the others — it HANGS them, wedged
inside a collective waiting for a participant that will never arrive.  The
:class:`ClusterCoordinator` is the replacement control plane, one per
process, built from three peer-observable primitives that all ride the
``utils.storage`` seam (a shared filesystem or the checkpoint bucket — the
visibility sharded checkpoints already require):

- **Membership + cross-host health.**  Each process beats
  (``resilience.detector.Heartbeat``) into the control directory; the
  phi-accrual :class:`~.detector.HeartbeatMonitor` is pointed at the
  PEERS' beats, and the deterministic leader — always the lowest live
  rank, a pure function of the live set, no election rounds — publishes
  epoch-numbered :class:`~.membership.MembershipView`\\ s.
- **Gang recovery.**  On a suspected host or a collective timeout, any
  survivor posts an epoch-scoped ABORT flag; every member's next
  bundle-edge check (:meth:`ClusterCoordinator.on_step`) sees it and
  raises :class:`GangAbortedError`, which unwinds the driver into its
  poison-rewind recovery path (``optim.optimizer``) — survivors exit the
  collective CLEANLY instead of hanging in it.  Recovery then runs
  :meth:`gang_recover`: rendezvous on a fresh view (epoch+1) so the
  whole gang re-enters ``optimize()`` together, not independently.
  Cluster-wide preemption rides the same machinery: a local SIGTERM is
  propagated as an epoch-scoped notice, so EVERY host takes the
  just-in-time checkpoint, not just the signalled one.
- **Peer-shard restore.**  The ZeRO-1 layout (``optim/train_step.py``,
  arXiv 2004.13336) makes recovery cheaper than checkpoint-rewind: each
  process periodically publishes its optimizer-state shard (plus, from
  the leader, the replicated params/EMA/model-state) into the
  :class:`PeerShardStore` on the control channel.  A rejoining or
  replacement process fetches current params and its shard from what its
  buddies published, falling back to the newest shard-complete
  checkpoint only when no complete peer step exists.  Restore path,
  MTTR, and bytes moved land in ``Metrics`` (``cluster.*``) and the
  flight recorder.

Chaos seams (``resilience.faults`` — deterministic, tier-1 testable in a
single process): ``cluster_host_loss`` (raises
:class:`~.faults.HostLostError` at a bundle edge), ``cluster_partition``
(a membership sweep sees no peers while the spec fires),
``cluster_slow_peer`` (delays this host's own beat), and
``cluster_preempt_notice`` (acts as a received cluster-wide preemption).

Clocks and sleeps are injectable (:class:`ClusterConfig`) so every
protocol path runs under tier-1 without wall-clock waits.
"""

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from bigdl_tpu.obs import flight, trace
from bigdl_tpu.resilience import faults
from bigdl_tpu.resilience.detector import Heartbeat, HeartbeatMonitor
from bigdl_tpu.resilience.membership import MembershipBoard, MembershipView
from bigdl_tpu.utils import storage
from bigdl_tpu.utils.log import get_logger

log = get_logger("bigdl_tpu.resilience")


class GangAbortedError(RuntimeError):
    """A PEER posted the abort flag for the current membership epoch —
    this process must exit its collective and join gang recovery.
    Classified like a host loss (``FailureCause.HOST_LOST``): the local
    process is healthy, the GANG is not."""

    def __init__(self, epoch: int, source_rank: int, reason: str):
        super().__init__(
            f"gang aborted (view epoch {epoch}) by rank {source_rank}: "
            f"{reason}")
        self.epoch = epoch
        self.source_rank = source_rank
        self.reason = reason


@dataclass
class ClusterConfig:
    """Knobs of one process's coordinator.  ``directory`` is the control
    channel — any path every process can see (shared filesystem,
    ``gs://…``, or ``memory://`` in tests)."""

    directory: str
    process_index: Optional[int] = None   # None: jax.process_index()
    heartbeat_interval_s: float = 5.0
    phi_threshold: float = 8.0
    rendezvous_timeout_s: float = 120.0
    rendezvous_poll_s: float = 0.2
    publish_keep: int = 2                 # complete peer steps retained
    # bundle edges serve abort/preempt checks from a cache refreshed by
    # the background sweep; at most one direct board probe per this many
    # seconds — so K=1 training never pays a storage listing per step
    edge_probe_interval_s: float = 1.0
    # metric federation (docs/observability.md §Federation): each sweep
    # publishes this host's counters/gauges/hist-quantiles onto the
    # board; the LEADER merges every host's snapshot into
    # cluster.host.*-labeled series, so one scrape of the leader's
    # /metrics shows the whole gang — stragglers included (their stale
    # snapshot shows with a growing cluster.host.age_s, never vanishes)
    metrics_federation: bool = True
    clock: Callable[[], float] = field(default=time.time)
    sleep: Callable[[float], None] = field(default=time.sleep)


def _hist_quantile(h: Dict, q: float) -> float:
    """Percentile from a ``LogHistogram.snapshot()`` dict (the board
    carries snapshots, not live histograms) — delegates to THE shared
    bucket-upper-bound rule so it cannot diverge from the local one."""
    from bigdl_tpu.obs.hist import percentile_from

    return percentile_from(h.get("counts", []), h.get("bounds", []),
                           int(h.get("n", 0)), float(h.get("max", 0.0)),
                           q)


# ---------------------------------------------------------------------------
# peer-shard store
# ---------------------------------------------------------------------------

_PARAMS_KEY = "__flat_params__"
_EMA_KEY = "__ema_flat__"
_MSTATE_PREFIX = "__mstate__/"


class PeerShardStore:
    """ZeRO-1 state over the control channel — the fast rung of the
    recovery ladder.

    Each rank publishes its own :func:`~bigdl_tpu.optim.checkpoint.
    local_opt_shards` dict per step (``peer-r<rank>-s<step>.npz``); the
    leader's payload additionally carries the replicated flat params, EMA,
    and model state, plus the JSON-safe driver state in its meta record.
    The meta (``.json``) is written LAST, manifest-style: a crash
    mid-publish leaves a data blob without a meta, which readers ignore.
    A step is **complete** — offerable to a restore — only when every rank
    of the publish-time process count has a meta AND some payload carries
    params.  A dead host stops publishing, so steps after its death never
    complete and the ladder falls back to the last complete step (or the
    checkpoint) instead of mixing generations."""

    def __init__(self, directory: str, keep: int = 2):
        self.directory = storage.join(directory, "peers")
        self.keep = keep
        storage.makedirs(self.directory)

    @staticmethod
    def _data_name(rank: int, step: int) -> str:
        return f"peer-r{rank:05d}-s{step:09d}.npz"

    @staticmethod
    def _meta_name(rank: int, step: int) -> str:
        return f"peer-r{rank:05d}-s{step:09d}.json"

    def publish(self, rank: int, step: int,
                opt_shards: Dict[str, np.ndarray], *, ranks: int,
                params: Optional[np.ndarray] = None,
                ema: Optional[np.ndarray] = None,
                mstate_flat: Optional[Dict[str, np.ndarray]] = None,
                driver_state: Optional[Dict[str, Any]] = None) -> int:
        """Write this rank's payload for ``step``; returns bytes written.
        Payload first, meta last (the completeness certificate)."""
        arrs = dict(opt_shards)
        if params is not None:
            arrs[_PARAMS_KEY] = np.asarray(params)
            if ema is not None:
                arrs[_EMA_KEY] = np.asarray(ema)
            for k, v in (mstate_flat or {}).items():
                arrs[_MSTATE_PREFIX + k] = np.asarray(v)
        with storage.open_file(
                storage.join(self.directory, self._data_name(rank, step)),
                "wb") as f:
            np.savez(f, **arrs)
        n_bytes = int(sum(a.nbytes for a in arrs.values()))
        storage.write_json(
            storage.join(self.directory, self._meta_name(rank, step)),
            {"rank": rank, "step": step, "ranks": int(ranks),
             "has_params": params is not None, "bytes": n_bytes,
             "driver_state": driver_state or {}})
        self.gc()
        return n_bytes

    def _metas_by_step(self) -> Dict[int, Dict[int, Dict]]:
        """{step: {rank: meta}} from ONE listing + the meta reads."""
        out: Dict[int, Dict[int, Dict]] = {}
        try:
            names = storage.listdir(self.directory)
        except (OSError, ImportError):
            return out
        for name in names:
            if not (name.startswith("peer-r") and name.endswith(".json")):
                continue
            try:
                meta = storage.read_json(
                    storage.join(self.directory, name))
                out.setdefault(int(meta["step"]), {})[int(meta["rank"])] \
                    = meta
            except (OSError, ValueError, KeyError):
                continue  # torn meta: that rank's publish is not certified
        return out

    @staticmethod
    def _complete(metas: Dict[int, Dict]) -> bool:
        ranks = {int(m.get("ranks", 0)) for m in metas.values()}
        if len(ranks) != 1:
            return False  # publishers disagree on the gang size: not one step
        n = ranks.pop()
        return (n > 0 and set(metas) == set(range(n))
                and any(m.get("has_params") for m in metas.values()))

    def complete_steps(self) -> List[int]:
        return sorted(s for s, metas in self._metas_by_step().items()
                      if self._complete(metas))

    def latest_complete_step(self) -> Optional[int]:
        steps = self.complete_steps()
        return steps[-1] if steps else None

    def fetch(self, step: int) -> Dict[str, Any]:
        """Read every rank's payload for a complete ``step``: the per-rank
        opt-shard dicts (``payloads``), the replicated params/EMA/model
        state from whichever rank published them, the driver state, and
        total bytes moved."""
        metas = self._metas_by_step().get(step, {})
        if not self._complete(metas):
            raise ValueError(f"peer store step {step} is not complete")
        payloads, params, ema = [], None, None
        mstate_flat: Dict[str, np.ndarray] = {}
        driver: Dict[str, Any] = {}
        n_bytes = 0
        for rank in sorted(metas):
            blob = storage.load_npz(storage.join(
                self.directory, self._data_name(rank, step)))
            n_bytes += int(sum(a.nbytes for a in blob.values()))
            shards = {}
            for k, v in blob.items():
                if k == _PARAMS_KEY:
                    params = v
                elif k == _EMA_KEY:
                    ema = v
                elif k.startswith(_MSTATE_PREFIX):
                    mstate_flat[k[len(_MSTATE_PREFIX):]] = v
                else:
                    shards[k] = v
            payloads.append(shards)
            if metas[rank].get("has_params"):
                driver = dict(metas[rank].get("driver_state") or {})
        return {"payloads": payloads, "params": params, "ema": ema,
                "mstate_flat": mstate_flat, "driver_state": driver,
                "bytes": n_bytes}

    def gc(self) -> None:
        """Keep the newest ``keep`` COMPLETE steps; anything strictly older
        than the oldest kept step goes.  Incomplete steps newer than that
        cutoff are publishes in flight, never garbage (the checkpoint-GC
        stance, ``optim.checkpoint._gc``)."""
        complete = self.complete_steps()
        if len(complete) <= self.keep:
            return
        cutoff = complete[-self.keep]
        try:
            names = storage.listdir(self.directory)
        except (OSError, ImportError):
            return
        for name in names:
            if not name.startswith("peer-r") or "-s" not in name:
                continue
            try:
                step = int(name.split("-s")[1].split(".")[0])
            except ValueError:
                continue
            if step < cutoff:
                storage.remove_tree(storage.join(self.directory, name),
                                    ignore_errors=True)


# ---------------------------------------------------------------------------
# the coordinator
# ---------------------------------------------------------------------------

class ClusterCoordinator:
    """One process's membership + gang-recovery + peer-restore agent.

    Wire-up: the :class:`~.supervisor.Supervisor` builds one when
    ``FailurePolicy.cluster_dir`` is set (or the driver attaches one via
    ``Optimizer.set_cluster``); the driver calls :meth:`on_step` at every
    bundle edge (served from a sweep-refreshed cache; direct board probes
    are rate-limited to one per ``edge_probe_interval_s``, so K=1
    training never pays a storage listing per step) and
    :meth:`publish_state` alongside every checkpoint save; :meth:`sweep`
    runs from the background heartbeat thread (``start(background=True)``)
    or explicitly in tests.  Two locks: ``_sweep_lock`` serializes whole
    sweep bodies (background thread vs ``gang_recover``'s poll loop),
    ``_lock`` guards the view/abort-cache state shared with the driver's
    bundle edge and is never held across storage I/O."""

    def __init__(self, config: ClusterConfig, metrics=None):
        self.cfg = config
        rank = config.process_index
        if rank is None:
            import jax

            rank = jax.process_index()
        self.rank = int(rank)
        if metrics is None:
            from bigdl_tpu.optim.metrics import global_metrics

            metrics = global_metrics()
        self.metrics = metrics
        self.board = MembershipBoard(config.directory)
        self.store = PeerShardStore(config.directory,
                                    keep=config.publish_keep)
        self.heartbeat = Heartbeat(
            config.directory, process_index=self.rank,
            interval_s=config.heartbeat_interval_s, clock=config.clock)
        self.monitor = HeartbeatMonitor(config.directory,
                                        clock=config.clock)
        self.view: Optional[MembershipView] = None
        self.preempt_pending = False
        self.last_restore_bytes = 0
        self._last_step = 0
        self._preempt_posted = False
        self._stale_preempt: set = set()
        self._suspected: set = set()
        self._topology = ""
        self._topology_warned = False
        # the epoch this process last JOINED (start or rendezvous): abort
        # flags are probed for every epoch in [joined, current] — a view
        # that advances between two bundle edges must not hide an abort
        # posted under the epoch this process was still training in
        self._joined_epoch = 0
        self._abort_seen: Optional[Tuple[int, Dict]] = None
        self._must_unwind: Optional[int] = None  # suspicion-abort epoch
        #                      posted by THIS process: its own edge must
        #                      unwind too (no local exception will)
        self._last_edge_probe = float("-inf")
        # two locks, two jobs: _sweep_lock serializes whole sweep bodies
        # (background thread vs gang_recover's poll loop — the monitor
        # and suspicion sets are sweep-only state), while _lock guards
        # the tiny state shared with the driver's bundle edge (view +
        # abort cache) and is NEVER held across storage I/O, so on_step
        # cannot stall behind a remote listing a sweep is doing
        self._sweep_lock = threading.Lock()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ----------------------------------------------------------
    def start(self, background: bool = False) -> "ClusterCoordinator":
        """Beat once and run a first sweep.  A (re)starting LEADER always
        bumps the view epoch — epoch-scoped abort flags and preemption
        notices from the previous incarnation die with the old epoch, so
        a restarted gang can never re-abort itself on stale state."""
        try:
            from bigdl_tpu.runtime.mesh import mesh_fingerprint

            self._topology = mesh_fingerprint()
        except Exception:  # pragma: no cover — backend not initializable
            self._topology = ""
        # notices left by the PREVIOUS incarnation must not re-preempt the
        # restarted gang.  The leader's start bump retires them with the
        # old epoch; a non-leader may still read the old view until that
        # bump lands, so the notices visible BEFORE our first sweep are
        # snapshotted as stale and ignored thereafter.
        v0 = self.board.current()
        if v0 is not None:
            self._stale_preempt = {(v0.epoch, r) for r in
                                   self.board.preempt_posted(v0.epoch)}
        self.sweep(reason="start", force_publish=True)
        with self._lock:
            self._joined_epoch = self._epoch()
            # the start sweep's cache refresh ran with joined still 0
            # and may hold a PREVIOUS incarnation's abort flag — the
            # restarted gang must not re-abort on it; the first edge
            # probe re-scans from the joined epoch only
            self._abort_seen = None
            self._must_unwind = None
            self._last_edge_probe = float("-inf")
        if background:
            self._stop.clear()

            def run():
                while not self._stop.wait(self.cfg.heartbeat_interval_s):
                    try:
                        self.sweep()
                    except Exception as e:  # sweep must never kill training
                        log.warning("cluster sweep failed: %s", e)

            self._thread = threading.Thread(
                target=run, name="bigdl-tpu-cluster", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.cfg.heartbeat_interval_s + 1)
            self._thread = None

    # -- membership ---------------------------------------------------------
    def _epoch(self) -> int:
        return self.view.epoch if self.view is not None else 0

    def is_leader(self) -> bool:
        v = self.view
        if v is None or not v.members:
            return True  # no agreed view yet: act, don't deadlock
        return self.rank == min(v.members)

    def sweep(self, now: Optional[float] = None,
              reason: Optional[str] = None,
              force_publish: bool = False) -> Optional[MembershipView]:
        """One membership pass: beat, read peers, and — when this process
        is the lowest live rank — publish a new view if membership
        changed, an abort was posted for the current epoch, or
        ``force_publish`` (process start).  A NEWLY suspected peer posts
        the gang abort: a dead member leaves every survivor wedged inside
        a collective with no local exception to unwind it."""
        with self._sweep_lock:
            return self._sweep_serialized(now, reason, force_publish)

    def _sweep_serialized(self, now, reason, force_publish):
        cfg = self.cfg
        faults.fire("cluster_slow_peer")  # straggler: own beat arrives late
        try:
            self.heartbeat.beat(step=self._last_step)
        except OSError as e:  # control dir blipped; next sweep retries
            log.warning("cluster heartbeat write failed: %s", e)
        if cfg.metrics_federation:
            try:
                self._publish_metrics()
            except Exception as e:  # noqa: BLE001 — observability only
                log.warning("cluster metric publish failed: %s", e)
        partitioned = False
        try:
            faults.fire("cluster_partition")
        except faults.PartitionError:
            partitioned = True  # this sweep sees NO peer state at all
        if partitioned:
            live = {self.rank}
            view = self.view
        else:
            live = set(self.monitor.alive(cfg.phi_threshold, now=now))
            live.add(self.rank)
            view = self.board.current() or self.view
        # suspicion accounting: members of the governing view not in the
        # live set (logged once per episode, like the Supervisor monitor)
        prev = set(view.members) if view is not None else set()
        new_suspects = sorted(r for r in (prev - live) - self._suspected
                              if r != self.rank)
        for r in new_suspects:
            log.error("cluster: peer process %d SUSPECTED dead "
                      "(phi > %.1f)", r, cfg.phi_threshold)
            self.metrics.inc("cluster.peers_suspected_total")
            flight.record("peer_suspected", process=r, by=self.rank)
        self._suspected = prev - live
        if new_suspects and not partitioned and view is not None \
                and self.board.abort_posted(view.epoch) is None:
            # heartbeat-detected death breaks the wedge: post the gang
            # abort so every member's bundle edge raises — including OUR
            # OWN (_must_unwind): the poster is healthy and would
            # otherwise stay inside the dead collective forever.  Gated
            # on not-partitioned: a blinded sweep suspects EVERYONE, and
            # in a real partition the board write cannot land anyway —
            # the majority side posts the abort that matters.  Posted
            # explicitly at view.epoch — the epoch the guard above
            # checked — which may be NEWER than self.view mid-sweep.
            self.abort("host(s) %s suspected dead" % new_suspects,
                       step=self._last_step, epoch=view.epoch)
            with self._lock:
                self._must_unwind = view.epoch
        # the leader rule: lowest live rank publishes
        abort = (view is not None
                 and self.board.abort_posted(view.epoch) is not None)
        changed = view is None or set(view.members) != live
        if min(live) == self.rank and (changed or abort or force_publish):
            epoch = view.epoch + 1 if view is not None else 1
            if reason is None:
                if view is None:
                    reason = "initial"
                elif live - prev and prev - live:
                    reason = "reconfigure"
                elif live - prev:
                    reason = "rejoin"
                elif prev - live:
                    reason = "host_loss"
                else:
                    reason = "abort_recovery" if abort else "republish"
            view = MembershipView(
                epoch=epoch, members=tuple(sorted(live)), leader=self.rank,
                step=self._last_step, reason=reason,
                topology=self._topology, published_by=self.rank,
                time=float(cfg.clock()))
            self.board.publish(view)
            self.board.gc(epoch)  # retire long-dead epochs' control files
            self.metrics.inc("cluster.views_total")
            flight.record("cluster_view", epoch=epoch,
                          members=list(view.members), reason=reason)
            log.warning("cluster: view %d published: members=%s (%s)",
                        epoch, list(view.members), reason)
        with self._lock:
            self.view = view
            joined = self._joined_epoch
            need_probe = self._abort_seen is None
        if view is not None:
            self.metrics.gauge("cluster.view_epoch", view.epoch)
            self.metrics.gauge("cluster.members", len(view.members))
            self.metrics.gauge("cluster.leader", view.leader)
            if not partitioned:
                # checked against the FINAL view of the sweep: a leader's
                # start-bump retires the previous epoch's notices before
                # they can be mistaken for fresh ones
                self._check_preempt(view)
                if need_probe:
                    # refresh the bundle-edge cache so on_step sees a
                    # peer's abort within one heartbeat interval even
                    # when its own probe window hasn't elapsed; probed
                    # OUTSIDE the edge lock (storage I/O must not stall
                    # the driver's next bundle edge)
                    hit = self._probe_abort_range(joined, view.epoch)
                    if hit is not None:
                        with self._lock:
                            if self._abort_seen is None:
                                self._abort_seen = hit
        if cfg.metrics_federation and not partitioned \
                and min(live) == self.rank:
            try:
                self.merge_peer_metrics()
            except Exception as e:  # noqa: BLE001 — observability only
                log.warning("cluster metric merge failed: %s", e)
        return view

    def _probe_abort_range(self, joined: int, epoch: int
                           ) -> Optional[Tuple[int, Dict]]:
        """The abort flag governing this process, if any: probe every
        epoch from the one we last JOINED through the current view's
        (bounded by the board's GC horizon).  A view published between
        two bundle edges must not hide an abort posted under the epoch
        we were still training in.  Pure storage reads — callers must
        NOT hold the edge lock."""
        hi = max(epoch, joined)
        lo = max(1, joined, hi - 4)
        for e in range(lo, hi + 1):
            a = self.board.abort_posted(e)
            if a is not None:
                return e, a
        return None

    def _probe_abort(self) -> Optional[Tuple[int, Dict]]:
        with self._lock:
            joined, epoch = self._joined_epoch, self._epoch()
        return self._probe_abort_range(joined, epoch)

    def _check_preempt(self, view: MembershipView) -> None:
        if self.preempt_pending:
            return
        notices = [r for r in self.board.preempt_posted(view.epoch)
                   if (view.epoch, r) not in self._stale_preempt]
        if notices:
            self.preempt_pending = True
            log.warning(
                "cluster: preemption notice from rank(s) %s (epoch %d) — "
                "this host checkpoints at its next bundle edge too",
                notices, view.epoch)
            flight.record("cluster_preempt_seen", ranks=notices,
                          epoch=view.epoch)

    # -- metric federation (docs/observability.md §Federation) --------------
    def _metrics_dir(self) -> str:
        d = storage.join(self.cfg.directory, "metrics")
        storage.makedirs(d)
        return d

    def _publish_metrics(self) -> None:
        """Write this host's metric snapshot (counters + gauges + hist
        quantiles) onto the board — one small JSON per host, overwritten
        each sweep, so the merge is one listing + one read per peer."""
        snap = self.metrics.snapshot(blocking=False)
        if snap is None:  # registry busy; next sweep publishes
            return
        flat: Dict[str, float] = {}
        for src in (snap["counters"], snap["gauges"]):
            for k, v in src.items():
                # the leader's own merged series must not re-publish —
                # cluster.host.cluster.host.* would grow without bound
                if k.startswith("cluster.host"):
                    continue
                flat[k] = float(v)
        for name, h in snap["hists"].items():
            # quantiles, not raw buckets: the gang-wide view answers
            # "which host's tail is burning", not full distributions
            if h["n"]:
                base, _, rest = name.partition("{")
                sfx = f"{{{rest}" if rest else ""
                flat[f"{base}.p50{sfx}"] = _hist_quantile(h, 50)
                flat[f"{base}.p99{sfx}"] = _hist_quantile(h, 99)
        storage.write_json(
            storage.join(self._metrics_dir(),
                         f"host-r{self.rank:05d}.json"),
            {"rank": self.rank, "t": float(self.cfg.clock()),
             "metrics": flat})

    def merge_peer_metrics(self) -> int:
        """LEADER: re-export every host's published snapshot as
        ``cluster.host.<name>{host="<rank>"}`` gauges (own rank included
        — the scrape reads uniformly), plus a per-host staleness gauge.
        A straggler's old snapshot stays visible with a growing
        ``cluster.host.age_s`` instead of silently dropping out of the
        scrape.  Returns the number of hosts merged."""
        try:
            names = storage.listdir(self._metrics_dir())
        except (OSError, ImportError):
            return 0
        now = float(self.cfg.clock())
        merged = 0
        for name in sorted(names):
            if not (name.startswith("host-r") and name.endswith(".json")):
                continue
            try:
                doc = storage.read_json(
                    storage.join(self._metrics_dir(), name))
                rank = int(doc["rank"])
                flat = doc.get("metrics", {})
            except (OSError, ValueError, KeyError):
                continue  # torn write: the next sweep reads the final file
            host_lb = f'host="{rank}"'
            for k, v in flat.items():
                try:
                    v = float(v)
                except (TypeError, ValueError):
                    continue
                # a published key may already carry labels
                # (serving.tenant_latency_seconds{tenant="a"}.p99 keeps
                # them before the quantile suffix was appended — split on
                # the FIRST brace): the host label joins the body
                base, _, rest = k.partition("{")
                body = rest[:-1] if rest.endswith("}") else rest
                lb = ",".join(x for x in (body, host_lb) if x)
                self.metrics.gauge(
                    f"cluster.host.{base}" + "{" + lb + "}", v)
            self.metrics.gauge("cluster.host.age_s",
                               max(0.0, now - float(doc.get("t", now))),
                               labels={"host": str(rank)})
            merged += 1
        self.metrics.gauge("cluster.hosts_reporting", merged)
        return merged

    # -- driver hooks -------------------------------------------------------
    def on_step(self, step: int, n_steps: int = 1) -> None:
        """Bundle-edge hook, mirroring ``faults.fire_bundle`` semantics:
        every step in ``[step, step + n_steps)`` is evaluated here, before
        the bundle dispatches.  Checks (in hazard order): injected
        preemption notices, posted notices/abort flags from peers, then
        injected host loss — which raises
        :class:`~.faults.HostLostError` into the driver's recovery path.
        Board state is served from the sweep-refreshed cache; a direct
        probe runs at most once per ``edge_probe_interval_s`` so K=1
        training never pays a storage listing per step."""
        self._last_step = step
        for s in range(step, step + n_steps):
            try:
                faults.fire("cluster_preempt_notice", step=s)
            except faults.PreemptNoticeFault:
                self.notify_preemption(source="injected")
        with self._lock:
            v = self.view
            joined = self._joined_epoch
            t = float(self.cfg.clock())
            probe = (v is not None and t - self._last_edge_probe
                     >= self.cfg.edge_probe_interval_s)
            if probe:
                self._last_edge_probe = t
            hit = self._abort_seen
            must = self._must_unwind
        if probe:
            # storage probes run WITHOUT the edge lock: a slow remote
            # board must not serialize against the background sweep
            if hit is None:
                hit = self._probe_abort_range(joined, v.epoch)
                if hit is not None:
                    with self._lock:
                        if self._abort_seen is None:
                            self._abort_seen = hit
                        hit = self._abort_seen
                        must = self._must_unwind
            self._check_preempt(v)
        if hit is not None:
            epoch, a = hit
            rank = int(a.get("rank", -1))
            if rank != self.rank or must == epoch:
                # a flag this process posted EXPLICITLY (driver
                # exception path) never re-raises on itself — the
                # driver is already recovering; a suspicion-abort
                # from our own sweep must unwind us like any peer
                raise GangAbortedError(epoch, rank,
                                       str(a.get("reason", "")))
        for s in range(step, step + n_steps):
            faults.fire("cluster_host_loss", step=s)

    def notify_preemption(self, source: str = "signal") -> None:
        """Propagate a LOCAL preemption cluster-wide: post the
        epoch-scoped notice every peer's next bundle edge / sweep will
        see.  Idempotent; a board blip never blocks the local
        just-in-time checkpoint."""
        self.preempt_pending = True
        if self._preempt_posted:
            return
        try:
            self.board.post_preempt(self._epoch(), self.rank)
            self._preempt_posted = True
        except OSError as e:
            log.warning("cluster: preemption notice post failed (%s); "
                        "local checkpoint proceeds regardless", e)
        self.metrics.inc("cluster.preempt_notices_total")
        flight.record("cluster_preempt", rank=self.rank,
                      epoch=self._epoch(), source=source)
        log.warning("cluster: preemption notice posted (rank %d, epoch %d,"
                    " %s)", self.rank, self._epoch(), source)

    # -- gang recovery ------------------------------------------------------
    def abort(self, reason: str, step: Optional[int] = None,
              epoch: Optional[int] = None) -> None:
        """Post the abort flag for ``epoch`` (default: the current view's;
        first poster wins); every peer's next ``on_step`` raises
        GangAbortedError."""
        epoch = self._epoch() if epoch is None else int(epoch)
        self.board.post_abort(epoch, self.rank, reason, step=step)
        self.metrics.inc("cluster.aborts_total")
        flight.record("cluster_abort", epoch=epoch, rank=self.rank,
                      reason=reason, step=step)
        log.warning("cluster: ABORT posted for epoch %d (%s)",
                    epoch, reason)

    def gang_recover(self, reason: str) -> MembershipView:
        """The survivor's recovery barrier: ensure the abort flag is up
        (so peers still inside the epoch exit too), wait for the
        post-abort view (the leader bumps the epoch even when membership
        is unchanged), then rendezvous on it — every member re-enters
        training together."""
        cfg = self.cfg
        with trace.span("cluster/gang_recover", reason=reason):
            # the barrier target is the epoch the governing abort is
            # posted AT (it may trail self.view when a sweep already
            # adopted the post-abort view) — waiting past the JOINED
            # epoch instead would rendezvous on the aborted view
            hit = self._probe_abort()
            if hit is not None:
                aborted = hit[0]
            else:
                aborted = self._epoch()
                self.abort(reason, step=self._last_step)
            deadline = cfg.clock() + cfg.rendezvous_timeout_s
            while True:
                view = self.sweep()
                if view is not None and view.epoch > aborted:
                    break
                if cfg.clock() > deadline:
                    raise TimeoutError(
                        f"gang recovery: no post-abort view appeared within "
                        f"{cfg.rendezvous_timeout_s}s (aborted epoch "
                        f"{aborted})")
                cfg.sleep(cfg.rendezvous_poll_s)
            return self.rendezvous(view)

    def rendezvous(self, view: Optional[MembershipView] = None,
                   timeout_s: Optional[float] = None) -> MembershipView:
        """Barrier on ``view``: ack its epoch and wait until every member
        has acked.  Raises ``TopologyChangedError`` when this process's
        device topology does not match the view's (a replacement host on
        different hardware must not join a collective gang)."""
        cfg = self.cfg
        view = view if view is not None else self.view
        if view is None:
            raise RuntimeError("rendezvous needs a membership view")
        self._check_topology(view)
        self.board.ack(view.epoch, self.rank)
        deadline = cfg.clock() + (timeout_s if timeout_s is not None
                                  else cfg.rendezvous_timeout_s)
        while True:
            missing = set(view.members) - set(self.board.acks(view.epoch))
            if not missing:
                break
            if cfg.clock() > deadline:
                raise TimeoutError(
                    f"rendezvous on epoch {view.epoch} timed out waiting "
                    f"for rank(s) {sorted(missing)}")
            cfg.sleep(cfg.rendezvous_poll_s)
        flight.record("cluster_rendezvous", epoch=view.epoch,
                      members=list(view.members))
        log.info("cluster: rendezvous complete on view %d (members %s)",
                 view.epoch, list(view.members))
        with self._lock:
            # this process has JOINED the new epoch: older epochs' abort
            # flags no longer govern it, and the edge cache restarts clean
            self._joined_epoch = max(self._joined_epoch, view.epoch)
            self._abort_seen = None
            self._must_unwind = None
            self._last_edge_probe = float("-inf")
        return view

    def _check_topology(self, view: MembershipView) -> None:
        if (view.topology and self._topology
                and view.topology != self._topology
                and view.published_by != self.rank):
            from bigdl_tpu.resilience.retry import TopologyChangedError

            raise TopologyChangedError(
                f"device topology {self._topology!r} does not match view "
                f"{view.epoch}'s {view.topology!r} — a replacement host "
                "must match the gang's hardware (or the gang restarts "
                "elastically at the new size)")

    def note_recovered(self, mttr_s: float) -> None:
        """Account one completed recovery: detection-to-resumed wall time
        into the ``cluster.mttr_s`` histogram (+ last-value gauge) and the
        recovery counter; the restore path/bytes were already counted by
        the resume ladder."""
        self.metrics.inc("cluster.recoveries_total")
        self.metrics.observe("cluster.mttr_s", mttr_s)
        self.metrics.gauge("cluster.last_mttr_s", mttr_s)
        flight.record("cluster_recover", mttr_s=round(mttr_s, 4),
                      epoch=self._epoch(),
                      restore_bytes=self.last_restore_bytes)

    # -- peer-shard restore -------------------------------------------------
    def publish_state(self, step_engine, driver_state: Dict[str, Any]
                      ) -> int:
        """Publish this rank's recovery payload for the driver state's
        iteration: its ZeRO-1 opt-state shard (O(state/process_count)
        device→host bytes, no cross-host allgather), plus — leader only —
        the replicated params/EMA/model state and the JSON-safe driver
        state.  Returns bytes written."""
        from bigdl_tpu.optim import checkpoint as ckpt_mod
        from bigdl_tpu.optim.train_step import host_fetch

        import jax

        step = int(driver_state.get("iteration", self._last_step))
        with trace.span("cluster/publish", step=step):
            shards = ckpt_mod.local_opt_shards(step_engine.opt_state)
            include = self.is_leader()
            params = (np.asarray(step_engine.flat_params)
                      if include else None)
            ema = (np.asarray(step_engine.ema_flat)
                   if include and step_engine.ema_flat is not None else None)
            mstate = (ckpt_mod._flatten_with_paths(
                host_fetch(step_engine.model_state)) if include else None)
            n = self.store.publish(
                self.rank, step, shards, ranks=jax.process_count(),
                params=params, ema=ema, mstate_flat=mstate,
                driver_state=ckpt_mod.jsonable_state(driver_state))
        self.metrics.inc("cluster.publishes_total")
        self.metrics.inc("cluster.publish_bytes_total", n)
        self.metrics.gauge("cluster.last_publish_step", step)
        flight.record("cluster_publish", step=step, bytes=n, rank=self.rank)
        return n

    def load_peer_state(self, step: int, opt_state_template,
                        model_state_template
                        ) -> Tuple[np.ndarray, Any, Any, Dict, Any]:
        """Reassemble full training state from the peer store at ``step``
        — the same return contract as ``checkpoint.load_checkpoint``
        (flat params, opt state, model state, driver state, EMA), so the
        driver's resume code is path-agnostic and peer restore is
        bit-identical to a checkpoint restore of the same step."""
        from bigdl_tpu.optim import checkpoint as ckpt_mod

        with trace.span("cluster/peer_restore", step=step):
            got = self.store.fetch(step)
            opt_flat = ckpt_mod.merge_flat_shards(got["payloads"],
                                                  opt_state_template)
            opt_state = ckpt_mod._unflatten_like(opt_state_template,
                                                 opt_flat)
            model_state = ckpt_mod._unflatten_like(model_state_template,
                                                   got["mstate_flat"])
        self.last_restore_bytes = int(got["bytes"])
        return (got["params"], opt_state, model_state,
                got["driver_state"], got["ema"])
