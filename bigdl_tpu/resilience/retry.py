"""Retry policies and failure classification.

Reference analog (unverified — mount empty): ``DistriOptimizer`` retries a
failed iteration batch from the last checkpoint up to
``bigdl.failure.retryTimes`` with a fixed sleep — one policy for every
failure.  Here retry behaviour is composable and cause-aware: a transient
storage hiccup deserves fast exponential backoff and many attempts, a
poisoned batch deserves few (replaying it will poison again unless the
data order changes), and a topology change is not retryable in place at
all — it needs an elastic resume.

Determinism: backoff jitter comes from a hash of (seed, attempt), not a
live RNG, so recovery timing is reproducible in tests.
"""

import math
import time
from dataclasses import dataclass, field
from enum import Enum
from typing import Callable, Dict, Optional

from bigdl_tpu.utils.log import get_logger

log = get_logger("bigdl_tpu.resilience")


class FailureCause(Enum):
    TRANSIENT_STORAGE = "transient_storage"
    POISONED_BATCH = "poisoned_batch"
    TOPOLOGY_CHANGE = "topology_change"
    PROCESS_FAILURE = "process_failure"
    STEP_FAILURE = "step_failure"
    # a PEER host died (or posted the gang abort flag): the local process
    # is healthy, the gang is not — recovery is coordinated (abort →
    # rendezvous on a new membership view → restore together), never an
    # independent local retry (resilience.cluster)
    HOST_LOST = "host_lost"
    UNKNOWN = "unknown"


class PoisonedStepError(RuntimeError):
    """Raised by the step watchdog on a NaN/Inf loss streak — the signal
    that the BATCH (or the LR) is the problem, not the infrastructure."""


class TopologyChangedError(RuntimeError):
    """The process set changed (preemption took a host; elastic restart
    brought a different count).  Not retryable in place: the supervisor
    must rebuild the engine and resume elastically."""


def classify(exc: BaseException) -> FailureCause:
    """Map an exception to a failure cause.  Injected faults carry their
    point; real exceptions classify by type, with OSError/timeouts as
    transient storage (the fsspec backends raise OSError subclasses).
    Wrapped errors (``raise X from Y`` — e.g. AsyncCheckpointer's
    escalation RuntimeError around a storage error) classify by the
    first recognizable link of the cause chain."""
    seen = set()
    e: Optional[BaseException] = exc
    while e is not None and id(e) not in seen:
        seen.add(id(e))
        cause = _classify_one(e)
        if cause is not FailureCause.UNKNOWN:
            return cause
        e = e.__cause__ or e.__context__
    return FailureCause.UNKNOWN


def _classify_one(exc: BaseException) -> FailureCause:
    from bigdl_tpu.resilience import faults

    if isinstance(exc, faults.ProcessKilledError):
        return FailureCause.PROCESS_FAILURE
    if isinstance(exc, faults.HostLostError):
        return FailureCause.HOST_LOST
    try:  # lazy: cluster imports this module
        from bigdl_tpu.resilience.cluster import GangAbortedError
    except ImportError:  # pragma: no cover — partial install
        pass
    else:
        if isinstance(exc, GangAbortedError):
            return FailureCause.HOST_LOST
    if isinstance(exc, (faults.InjectedStorageError,
                        faults.InjectedCheckpointWriteError)):
        return FailureCause.TRANSIENT_STORAGE
    if isinstance(exc, faults.InjectedStepFailure):
        return FailureCause.STEP_FAILURE
    if isinstance(exc, TopologyChangedError):
        return FailureCause.TOPOLOGY_CHANGE
    if isinstance(exc, (PoisonedStepError, FloatingPointError)):
        return FailureCause.POISONED_BATCH
    if isinstance(exc, (OSError, TimeoutError, ConnectionError)):
        return FailureCause.TRANSIENT_STORAGE
    import re

    # word-bounded: "info"/"nanosecond" must not read as numerics trouble
    if re.search(r"\b(nan|inf|infinity|non-finite)\b", str(exc).lower()):
        return FailureCause.POISONED_BATCH
    return FailureCause.UNKNOWN


@dataclass
class RetryPolicy:
    """Bounded retries with exponential backoff + deterministic jitter."""

    max_retries: int = 5
    base_s: float = 1.0
    multiplier: float = 2.0
    max_s: float = 60.0
    jitter: float = 0.1   # ± fraction of the backoff
    seed: int = 0

    def backoff(self, attempt: int) -> float:
        """Sleep before retry ``attempt`` (1-based): exponential, capped,
        with hash-based jitter in ``[-jitter, +jitter)`` of the value."""
        if attempt < 1:
            attempt = 1
        raw = min(self.max_s,
                  self.base_s * self.multiplier ** (attempt - 1))
        if not self.jitter:
            return raw
        from bigdl_tpu.resilience.faults import _unit_hash

        u = 2.0 * _unit_hash(self.seed, "backoff", attempt) - 1.0
        return max(0.0, raw * (1.0 + self.jitter * u))

    def call(self, fn: Callable, *args,
             retryable: Callable[[BaseException], bool] = lambda e: True,
             describe: str = "operation", sleep=time.sleep, **kwargs):
        """Run ``fn`` under this policy; re-raises the last error once
        retries are exhausted or the error is not ``retryable``."""
        attempt = 0
        while True:
            try:
                return fn(*args, **kwargs)
            except Exception as e:
                attempt += 1
                if attempt > self.max_retries or not retryable(e):
                    raise
                delay = self.backoff(attempt)
                log.warning("%s failed (%s: %s); retry %d/%d in %.2fs",
                            describe, type(e).__name__, e, attempt,
                            self.max_retries, delay)
                sleep(delay)


# fast-exponential for storage blips; nearly-no-retry for poisoned batches
# (replaying the same plan poisons again); none for topology changes; a
# few patient retries for a lost host (the gang rendezvous + peer-shard
# restore between attempts is the actual recovery work)
_DEFAULT_BY_CAUSE: Dict[FailureCause, RetryPolicy] = {
    FailureCause.TRANSIENT_STORAGE: RetryPolicy(
        max_retries=8, base_s=0.5, max_s=30.0),
    FailureCause.POISONED_BATCH: RetryPolicy(max_retries=1, base_s=0.0),
    FailureCause.TOPOLOGY_CHANGE: RetryPolicy(max_retries=0),
    FailureCause.HOST_LOST: RetryPolicy(max_retries=4, base_s=0.5,
                                        max_s=30.0),
}


@dataclass
class FailurePolicy:
    """The engine-level fault-tolerance contract (``EngineConfig`` carries
    one; the :class:`..supervisor.Supervisor` enforces it).

    ``max_restarts`` bounds TOTAL supervisor-level recoveries across
    causes; ``by_cause`` overrides the per-cause retry policy (defaults:
    aggressive for transient storage, a single retry for poisoned
    batches, none for topology changes — those resume elastically
    instead)."""

    max_restarts: int = 5
    default_retry: RetryPolicy = field(default_factory=RetryPolicy)
    by_cause: Dict[FailureCause, RetryPolicy] = field(default_factory=dict)
    # detection
    heartbeat_dir: Optional[str] = None
    heartbeat_interval_s: float = 5.0
    heartbeat_phi_threshold: float = 8.0
    watchdog_step_timeout_s: float = 600.0
    nan_patience: int = 3
    # recovery
    restart_from_scratch: bool = True  # no valid checkpoint: restart vs give up
    # cluster control plane (docs/resilience.md §Multi-host recovery):
    # setting cluster_dir makes the Supervisor run a ClusterCoordinator —
    # membership views + gang recovery + peer-shard restore over that
    # shared directory.  Supersedes heartbeat_dir (the coordinator beats
    # and monitors itself; a separate observe-only monitor would double-
    # count suspicions).  BIGDL_TPU_CLUSTER_DIR sets it fleet-wide.
    cluster_dir: Optional[str] = None
    cluster_rendezvous_timeout_s: float = 120.0

    def policy_for(self, cause: FailureCause) -> RetryPolicy:
        if cause in self.by_cause:
            return self.by_cause[cause]
        return _DEFAULT_BY_CAUSE.get(cause, self.default_retry)
