"""``bigdl_tpu.resilience`` — fault-tolerant training.

Four layers (see ``docs/resilience.md`` for the failure model):

- :mod:`.faults`    — deterministic fault injection (tests, bench_probe)
- :mod:`.detector`  — heartbeats (phi-accrual) + step watchdog
- :mod:`.retry`     — retry policies, failure classification, FailurePolicy
- :mod:`.supervisor`— the optimize() retry loop; elastic resume guarantee

``Supervisor``/``supervise`` import lazily: they pull in the optimizer and
engine layers, which themselves import the leaf modules above — an eager
import here would cycle.
"""

from bigdl_tpu.resilience import faults
from bigdl_tpu.resilience.detector import (Heartbeat, HeartbeatMonitor,
                                           StepWatchdog)
from bigdl_tpu.resilience.faults import (FaultInjector, FaultSpec,
                                         InjectedFault,
                                         InjectedPredictError)
from bigdl_tpu.resilience.retry import (FailureCause, FailurePolicy,
                                        PoisonedStepError, RetryPolicy,
                                        TopologyChangedError, classify)

__all__ = [
    "faults", "FaultInjector", "FaultSpec", "InjectedFault",
    "InjectedPredictError",
    "Heartbeat", "HeartbeatMonitor", "StepWatchdog",
    "FailureCause", "FailurePolicy", "PoisonedStepError", "RetryPolicy",
    "TopologyChangedError", "classify",
    "Supervisor", "supervise",
]


def __getattr__(name):
    if name in ("Supervisor", "supervise"):
        from bigdl_tpu.resilience import supervisor as _sup

        return getattr(_sup, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
