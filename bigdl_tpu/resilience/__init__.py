"""``bigdl_tpu.resilience`` — fault-tolerant training.

Six layers (see ``docs/resilience.md`` for the failure model):

- :mod:`.faults`    — deterministic fault injection (tests, bench_probe)
- :mod:`.detector`  — heartbeats (phi-accrual) + step watchdog
- :mod:`.retry`     — retry policies, failure classification, FailurePolicy
- :mod:`.membership`— epoch-numbered views over a shared control channel
- :mod:`.cluster`   — gang recovery + peer-shard restore (pod scale)
- :mod:`.supervisor`— the optimize() retry loop; elastic resume guarantee

``Supervisor``/``supervise`` and the cluster layer import lazily: they
pull in the optimizer and engine layers, which themselves import the leaf
modules above — an eager import here would cycle.
"""

from bigdl_tpu.resilience import faults
from bigdl_tpu.resilience.detector import (Heartbeat, HeartbeatMonitor,
                                           StepWatchdog)
from bigdl_tpu.resilience.faults import (FaultInjector, FaultSpec,
                                         HostLostError, InjectedFault,
                                         InjectedPredictError)
from bigdl_tpu.resilience.membership import MembershipBoard, MembershipView
from bigdl_tpu.resilience.retry import (FailureCause, FailurePolicy,
                                        PoisonedStepError, RetryPolicy,
                                        TopologyChangedError, classify)

__all__ = [
    "faults", "FaultInjector", "FaultSpec", "InjectedFault",
    "InjectedPredictError", "HostLostError",
    "Heartbeat", "HeartbeatMonitor", "StepWatchdog",
    "MembershipBoard", "MembershipView",
    "FailureCause", "FailurePolicy", "PoisonedStepError", "RetryPolicy",
    "TopologyChangedError", "classify",
    "Supervisor", "supervise",
    "ClusterConfig", "ClusterCoordinator", "GangAbortedError",
    "PeerShardStore",
]

_CLUSTER = ("ClusterConfig", "ClusterCoordinator", "GangAbortedError",
            "PeerShardStore")


def __getattr__(name):
    if name in ("Supervisor", "supervise"):
        from bigdl_tpu.resilience import supervisor as _sup

        return getattr(_sup, name)
    if name in _CLUSTER:
        from bigdl_tpu.resilience import cluster as _cluster

        return getattr(_cluster, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
