"""Deterministic fault injection — the testable half of fault tolerance.

Reference analog (unverified — mount empty): the reference exercises its
driver retry loop (``bigdl.failure.retryTimes``) only against real executor
loss; there is no first-class injection harness.  Here every recovery path
must be exercisable on CPU under tier-1, so failures are INJECTED at named
points with deterministic triggers: a run with the same fault plan fires the
same faults at the same invocations, every time, on every process.

Injection points (instrumented call sites in parentheses):

- ``step_fail``             — raise inside the train iteration
                              (``Optimizer._one_bundle``; with step
                              bundling every step of the bundle's range is
                              evaluated at the bundle edge)
- ``checkpoint_write_fail`` — raise mid-checkpoint, after blobs and BEFORE
                              the manifest (``checkpoint.save_checkpoint``),
                              leaving the partial prefix readers must skip
- ``storage_io_fail``       — raise from the storage seam
                              (``utils.storage.open_file``)
- ``process_kill``          — ``os._exit`` (or raise, for in-process tests)
                              from the train iteration
- ``slow_host``             — sleep inside the train iteration (straggler)

Triggers per spec: ``at_step`` (fires when the instrumented site passes that
step), ``every`` (every Nth invocation), ``probability`` (deterministic
pseudo-randomness: a hash of (seed, point, invocation count) — NOT a live
RNG, so two runs of the same plan agree).  ``max_fires`` bounds total fires
(defaults to 1 for ``at_step`` specs so a resumed run that replays the step
does not die forever on it).

Config: programmatic (``install([FaultSpec(...)])``) or env —

    BIGDL_TPU_FAULTS="step_fail@5;checkpoint_write_fail:p=0.5;slow_host@3:delay=0.2"

entries split on ``;``, each ``point[@step][:key=val[:key=val]...]`` with
keys ``p`` (probability), ``every``, ``max`` (max_fires), ``delay``
(seconds, slow_host), ``seed``, ``action`` (``raise``/``exit``/``sleep``).
The env plan is read once, lazily, at the first instrumented call.
"""

import os
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from bigdl_tpu.obs import flight
from bigdl_tpu.utils.log import get_logger

log = get_logger("bigdl_tpu.resilience")

POINTS = ("step_fail", "checkpoint_write_fail", "storage_io_fail",
          "process_kill", "slow_host",
          # serving chaos seams (instrumented in ServingServer._process):
          # - serving_predict_fail — raise in place of predict (a dying
          #   model replica; feeds the degradation/breaker machinery)
          # - serving_worker_kill  — os._exit mid-batch (a preempted pool
          #   worker dying with requests in flight)
          # - serving_slow_batch   — sleep before predict (a straggling
          #   batch; drives deadline expiry downstream)
          "serving_predict_fail", "serving_worker_kill",
          "serving_slow_batch",
          # cluster chaos seams (instrumented in resilience.cluster /
          # resilience.membership) — the single-process simulation of
          # pod-scale failures, so gang recovery is tier-1 testable:
          # - cluster_host_loss     — raise HostLostError at a bundle edge
          #   (a peer host died mid-collective; survivors must gang-abort)
          # - cluster_partition     — while firing, a membership sweep
          #   sees no peer heartbeats (network partition; heals when the
          #   spec's max_fires is exhausted)
          # - cluster_slow_peer     — sleep before this host's own beat
          #   (a straggler whose beats arrive late, driving peer phi up)
          # - cluster_preempt_notice — acts as a received cluster-wide
          #   preemption notice (maintenance event on SOME host; every
          #   member must take the just-in-time checkpoint)
          "cluster_host_loss", "cluster_partition", "cluster_slow_peer",
          "cluster_preempt_notice",
          # decode-fleet chaos seams (docs/serving.md §Fleet fault
          # tolerance) — the stateful-serving failure modes the pool's
          # failover/migration machinery must absorb:
          # - fleet_worker_kill    — os._exit in a decode worker with
          #   streams mid-flight (kill -9 / preemption; the proxy must
          #   fail the streams over, not drop them)
          # - fleet_handoff_corrupt — fired at the migration/handoff
          #   export seam: the shipped blob arrives corrupt, so the
          #   importer must reject it cleanly and the stream complete
          #   via re-prefill failover instead
          # - fleet_stream_sever   — raise at the proxy's stream-relay
          #   seam (connection reset mid-stream without the worker
          #   dying; exercises resume with a live victim)
          # - fleet_health_stale   — raise in the proxy's /health probe
          #   (a worker that stops answering health without dying;
          #   drives snapshot invalidation + re-route)
          "fleet_worker_kill", "fleet_handoff_corrupt",
          "fleet_stream_sever", "fleet_health_stale")


class InjectedFault(RuntimeError):
    """Base of every injected failure; ``point`` names the injection site."""

    def __init__(self, point: str, step=None, count: int = 0):
        super().__init__(
            f"injected fault {point!r}"
            + (f" at step {step}" if step is not None else "")
            + f" (invocation {count})")
        self.point = point
        self.step = step
        self.count = count


class InjectedStepFailure(InjectedFault):
    pass


class InjectedCheckpointWriteError(InjectedFault):
    pass


class InjectedStorageError(InjectedFault, OSError):
    """Classified as transient storage by :func:`..retry.classify`."""


class ProcessKilledError(InjectedFault):
    """``process_kill`` in ``action="raise"`` mode (in-process tests)."""


class InjectedPredictError(InjectedFault):
    """``serving_predict_fail`` — a replica's predict dying; the serving
    degradation machinery must treat it exactly like a real model error."""


class HostLostError(InjectedFault):
    """``cluster_host_loss`` — a peer host vanished under the gang.  The
    real-world analog is a collective that times out because one
    participant died; survivors must abort the collective, rendezvous on
    a new membership view, and restore together (resilience.cluster)."""


class PartitionError(InjectedFault):
    """``cluster_partition`` in ``action="raise"`` mode; the default
    instrumentation (membership sweep) catches it and simulates the
    partition instead of propagating."""


class PreemptNoticeFault(InjectedFault):
    """``cluster_preempt_notice`` — caught by the instrumented site
    (ClusterCoordinator) and turned into a cluster-wide preemption
    event, never propagated as an error."""


class StreamSeveredError(InjectedFault, ConnectionResetError):
    """``fleet_stream_sever`` — the proxy's relay loop sees it exactly
    as a worker connection dying mid-stream, triggering failover while
    the worker itself stays healthy."""


class HandoffCorruptFault(InjectedFault):
    """``fleet_handoff_corrupt`` — raised at the handoff/migration
    export seam; the caller degrades to the re-prefill failover path."""


class HealthStaleFault(InjectedFault):
    """``fleet_health_stale`` — a /health probe that never answers; the
    proxy treats the worker as unprobeable and routes around it."""


_EXC = {
    "step_fail": InjectedStepFailure,
    "checkpoint_write_fail": InjectedCheckpointWriteError,
    "storage_io_fail": InjectedStorageError,
    "process_kill": ProcessKilledError,
    "slow_host": InjectedFault,
    "serving_predict_fail": InjectedPredictError,
    "serving_worker_kill": ProcessKilledError,
    "serving_slow_batch": InjectedFault,
    "cluster_host_loss": HostLostError,
    "cluster_partition": PartitionError,
    "cluster_slow_peer": InjectedFault,
    "cluster_preempt_notice": PreemptNoticeFault,
    "fleet_worker_kill": ProcessKilledError,
    "fleet_handoff_corrupt": HandoffCorruptFault,
    "fleet_stream_sever": StreamSeveredError,
    "fleet_health_stale": HealthStaleFault,
}


@dataclass
class FaultSpec:
    point: str
    at_step: Optional[int] = None
    probability: float = 0.0
    every: Optional[int] = None
    max_fires: Optional[int] = None   # None: 1 when at_step set, else ∞
    delay_s: float = 0.2              # slow_host sleep
    action: Optional[str] = None      # raise | exit | sleep (point default)
    seed: int = 0

    def __post_init__(self):
        if self.point not in POINTS:
            raise ValueError(
                f"unknown fault point {self.point!r}; one of {POINTS}")
        if self.action is None:
            self.action = {"slow_host": "sleep",
                           "serving_slow_batch": "sleep",
                           "cluster_slow_peer": "sleep",
                           "process_kill": "exit",
                           "serving_worker_kill": "exit",
                           "fleet_worker_kill": "exit"}.get(
                               self.point, "raise")
        if self.max_fires is None and self.at_step is not None:
            self.max_fires = 1


def _unit_hash(seed: int, point: str, count: int) -> float:
    """Deterministic pseudo-uniform in [0, 1) — the probability trigger.
    A hash, not an RNG stream: trigger decisions depend only on
    (seed, point, invocation index), never on evaluation order."""
    import hashlib

    h = hashlib.blake2b(f"{seed}:{point}:{count}".encode(), digest_size=8)
    return int.from_bytes(h.digest(), "little") / 2.0 ** 64


class FaultInjector:
    """Evaluates a fault plan at instrumented points.  Records every fire in
    ``events`` (``(point, step, invocation)``) so tests can assert on the
    exact pattern."""

    def __init__(self, specs: List[FaultSpec]):
        self.specs = list(specs)
        self._invocations: Dict[str, int] = {}
        self._fires: Dict[int, int] = {}
        self.events: List[Tuple[str, Optional[int], int]] = []

    def fire(self, point: str, step: Optional[int] = None) -> None:
        """Called by an instrumented site; raises/sleeps/exits per plan."""
        count = self._invocations.get(point, 0)
        self._invocations[point] = count + 1
        for i, spec in enumerate(self.specs):
            if spec.point != point:
                continue
            if not self._should_fire(i, spec, step, count):
                continue
            self._fires[i] = self._fires.get(i, 0) + 1
            self.events.append((point, step, count))
            log.warning("fault injection: firing %r (step=%s, invocation %d)",
                        point, step, count)
            # the postmortem must show the fault BEFORE its consequences
            flight.record("fault_injected", point=point, step=step,
                          invocation=count, action=spec.action)
            if spec.action == "sleep":
                time.sleep(spec.delay_s)
            elif spec.action == "exit":
                # os._exit bypasses excepthook/atexit/signal handlers, so
                # an armed flight recorder must dump HERE or the fault
                # event dies with the process
                flight.dump_if_installed(f"injected {point} (exit)")
                os._exit(113)
            else:
                raise _EXC[point](point, step=step, count=count)

    def _should_fire(self, i: int, spec: FaultSpec,
                     step: Optional[int], count: int) -> bool:
        if spec.max_fires is not None \
                and self._fires.get(i, 0) >= spec.max_fires:
            return False
        if spec.at_step is not None:
            return step == spec.at_step
        if spec.every is not None:
            return (count + 1) % spec.every == 0
        if spec.probability > 0.0:
            return _unit_hash(spec.seed, spec.point, count) < spec.probability
        return False


# -- module-level plan (what the instrumented sites consult) ---------------

_injector: Optional[FaultInjector] = None
_env_checked = False


def install(specs) -> FaultInjector:
    """Install a fault plan process-wide; returns the injector (its
    ``events`` list is the test observability surface)."""
    global _injector, _env_checked
    if isinstance(specs, FaultInjector):
        _injector = specs
    else:
        _injector = FaultInjector(list(specs))
    _env_checked = True
    return _injector


def clear() -> None:
    global _injector, _env_checked
    _injector = None
    _env_checked = True  # an explicit clear() also disables the env plan


def get() -> Optional[FaultInjector]:
    return _injector


def fire(point: str, step: Optional[int] = None) -> None:
    """The instrumented-site entry: near-zero cost when no plan is
    installed (one None check after the lazy env probe)."""
    global _injector, _env_checked
    if _injector is None:
        if _env_checked:
            return
        _env_checked = True
        plan = os.environ.get("BIGDL_TPU_FAULTS")
        if not plan:
            return
        _injector = FaultInjector(parse_plan(plan))
    _injector.fire(point, step=step)


def fire_step(step: int) -> None:
    """All step-scoped points, in hazard order: a straggler is slow BEFORE
    it fails, and a kill beats a clean exception."""
    fire("slow_host", step=step)
    fire("process_kill", step=step)
    fire("step_fail", step=step)


def fire_bundle(step: int, n_steps: int = 1) -> None:
    """Step-scoped points for a K-step bundle dispatched as ONE XLA
    program (``Optimizer._one_bundle``): the host only regains control at
    bundle edges, so every step in ``[step, step + n_steps)`` is evaluated
    here, before the bundle dispatches — an ``at_step`` plan keeps firing
    at its exact step regardless of bundling, and the whole bundle rewinds
    to its start on recovery."""
    if _injector is None and _env_checked:
        return  # keep the no-plan path one branch, not n_steps calls
    for s in range(step, step + n_steps):
        fire_step(s)


def parse_plan(text: str) -> List[FaultSpec]:
    """Parse the ``BIGDL_TPU_FAULTS`` grammar (module docstring)."""
    specs = []
    for entry in filter(None, (e.strip() for e in text.split(";"))):
        head, *opts = entry.split(":")
        point, at = (head.split("@", 1) + [None])[:2]
        kw = dict(point=point.strip(),
                  at_step=int(at) if at is not None else None)
        for opt in opts:
            k, _, v = opt.partition("=")
            k = k.strip()
            if k == "p":
                kw["probability"] = float(v)
            elif k == "every":
                kw["every"] = int(v)
            elif k == "max":
                kw["max_fires"] = int(v)
            elif k == "delay":
                kw["delay_s"] = float(v)
            elif k == "seed":
                kw["seed"] = int(v)
            elif k == "action":
                kw["action"] = v.strip()
            else:
                raise ValueError(f"unknown fault option {k!r} in {entry!r}")
        specs.append(FaultSpec(**kw))
    return specs
