"""Failure detection: multi-process heartbeats + single-process watchdog.

Reference analog (unverified — mount empty): the reference leans on Spark's
executor liveness (driver heartbeat timeouts) to learn a worker died.  The
TPU multi-controller world has no driver, so liveness is peer-observable
state: each process writes a heartbeat file under a shared directory
(checkpoint bucket or shared filesystem — the same visibility requirement
sharded checkpoints already impose), and any process can run a monitor over
the set.

Suspicion is phi-accrual style (Hayashibara et al.; the Akka/Cassandra
detector): instead of a fixed timeout, the monitor keeps a window of
inter-arrival times per peer and reports a CONTINUOUS suspicion level

    phi(elapsed) = -log10( P(a beat takes longer than elapsed) )

under a normal model of the window.  phi ≈ 1 means "this gap would happen
~10% of the time", phi ≥ 8 is practical certainty of death.  The caller
picks the threshold (``FailurePolicy.heartbeat_phi_threshold``) to trade
detection latency against false positives from GC/compile pauses.

The single-process :class:`StepWatchdog` covers the failures heartbeats
cannot see: a HUNG step (the process is alive, the chip is wedged) and a
POISONED step (loss went NaN/Inf — the process is healthy but the model is
dying).  Both are flagged from the driver loop's own observations; the NaN
streak raises :class:`~.retry.PoisonedStepError` so the recovery path
classifies it as data, not infrastructure.

All clocks are injectable (``clock=``) so tests advance time without
sleeping.
"""

import json
import math
import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from bigdl_tpu.resilience.retry import PoisonedStepError
from bigdl_tpu.utils import storage
from bigdl_tpu.utils.log import get_logger

log = get_logger("bigdl_tpu.resilience")


def _hb_path(directory: str, index: int) -> str:
    return storage.join(directory, f"hb-{index:05d}.json")


class Heartbeat:
    """Per-process heartbeat writer.  ``beat()`` writes one beat (tests,
    or callers that beat from their own loop); ``start()`` spawns a daemon
    thread beating every ``interval_s``.

    ``directory`` may be local or a remote URI (``gs://…`` — the natural
    choice on a multi-host pod, matching the checkpoint bucket; routed
    through ``utils.storage`` like checkpoints are).  Local writes are
    tmp+replace so a reader never sees a torn file; a remote object PUT
    is already atomic."""

    def __init__(self, directory: str, process_index: Optional[int] = None,
                 interval_s: float = 5.0, clock: Callable[[], float] = time.time):
        if process_index is None:
            import jax

            process_index = jax.process_index()
        self._remote = storage.is_remote(directory)
        storage.makedirs(directory)
        self.path = _hb_path(directory, process_index)
        self.process_index = process_index
        self.interval_s = interval_s
        self._clock = clock
        self._step = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def beat(self, step: Optional[int] = None) -> None:
        if step is not None:
            self._step = step
        rec = {"process_index": self.process_index, "pid": os.getpid(),
               "step": self._step, "time": self._clock()}
        if self._remote:
            storage.write_json(self.path, rec)
            return
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(rec, f)
        os.replace(tmp, self.path)

    def start(self) -> "Heartbeat":
        def run():
            while not self._stop.wait(self.interval_s):
                try:
                    self.beat()
                except OSError as e:  # shared dir blipped; next beat retries
                    log.warning("heartbeat write failed: %s", e)

        self.beat()
        self._thread = threading.Thread(
            target=run, name="bigdl-tpu-heartbeat", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval_s + 1)
            self._thread = None


class HeartbeatMonitor:
    """Phi-accrual suspicion over every ``hb-*.json`` in a directory."""

    def __init__(self, directory: str, window: int = 32,
                 min_std_s: float = 0.1,
                 clock: Callable[[], float] = time.time):
        self.directory = directory
        self.window = window
        self.min_std_s = min_std_s  # floor: a perfectly regular beat
        #                             history must not make phi explode
        self._clock = clock
        self._last: Dict[int, float] = {}
        self._steps: Dict[int, int] = {}
        self._intervals: Dict[int, deque] = {}

    def poll(self) -> Dict[int, float]:
        """Read the current beat files; returns {process_index: beat_time}.
        Call periodically (or before ``suspects``).  Works on local dirs
        and remote URIs alike (the ``utils.storage`` seam)."""
        seen = {}
        try:
            names = storage.listdir(self.directory)
        except (OSError, ImportError):
            return seen
        for name in names:
            if not (name.startswith("hb-") and name.endswith(".json")):
                continue
            try:
                rec = storage.read_json(
                    storage.join(self.directory, name))
            except (OSError, ValueError):
                continue  # torn/unreadable: count as a missed beat
            idx = int(rec["process_index"])
            t = float(rec["time"])
            seen[idx] = t
            prev = self._last.get(idx)
            if prev is not None and t > prev:
                self._intervals.setdefault(
                    idx, deque(maxlen=self.window)).append(t - prev)
            if prev is None or t > prev:
                self._last[idx] = t
                self._steps[idx] = int(rec.get("step", 0) or 0)
        return seen

    def phi(self, process_index: int, now: Optional[float] = None) -> float:
        """Suspicion level for one peer; 0 when it just beat, +inf when it
        was never seen at all."""
        last = self._last.get(process_index)
        if last is None:
            return float("inf")
        now = self._clock() if now is None else now
        elapsed = max(0.0, now - last)
        ivals = self._intervals.get(process_index)
        if ivals:
            mean = sum(ivals) / len(ivals)
            var = sum((x - mean) ** 2 for x in ivals) / len(ivals)
            std = max(math.sqrt(var), self.min_std_s)
        else:  # single beat so far: assume it meant to beat again soon
            mean, std = 1.0, max(1.0, self.min_std_s)
        # P(interval > elapsed) under N(mean, std): survival via erfc
        z = (elapsed - mean) / (std * math.sqrt(2.0))
        p_later = 0.5 * math.erfc(z)
        if p_later <= 0.0:
            return float("inf")
        return -math.log10(p_later)

    def suspects(self, threshold: float = 8.0,
                 now: Optional[float] = None) -> List[int]:
        """Process indices whose phi exceeds ``threshold`` (poll first)."""
        self.poll()
        return sorted(i for i in self._last
                      if self.phi(i, now=now) > threshold)

    def alive(self, threshold: float = 8.0,
              now: Optional[float] = None) -> List[int]:
        """The complement of ``suspects``: every ever-seen peer whose phi
        is at or under ``threshold`` (poll first) — the live set a
        membership sweep turns into a view (``resilience.cluster``)."""
        self.poll()
        return sorted(i for i in self._last
                      if self.phi(i, now=now) <= threshold)

    def peer_step(self, process_index: int) -> Optional[int]:
        """The training step the peer last reported in its beat — lets a
        monitor (or a postmortem) see not just THAT a peer is alive but
        where its driver loop is."""
        return self._steps.get(process_index)


class StepWatchdog:
    """Single-process step health: hung-step detection + NaN-streak.

    The driver loop reports ``step_started``/``observe_loss``; ``hung()``
    (or the optional background ``start()`` thread) flags a step that has
    been in flight longer than ``step_timeout_s``.  A hang cannot be
    safely interrupted from Python (the thread is blocked in XLA), so the
    watchdog's job is to make the condition VISIBLE — ``on_hang`` may
    escalate (e.g. ``os.kill`` for a supervisor restart)."""

    def __init__(self, step_timeout_s: float = 600.0, nan_patience: int = 3,
                 on_hang: Optional[Callable[[int, float], None]] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.step_timeout_s = step_timeout_s
        self.nan_patience = nan_patience
        self.on_hang = on_hang
        self._clock = clock
        self._step = -1
        self._started: Optional[float] = None
        self._nan_streak = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._hang_reported = False

    def step_started(self, step: int) -> None:
        self._step = step
        self._started = self._clock()
        self._hang_reported = False

    def observe_loss(self, step: int, loss: float) -> None:
        """Feed an OBSERVED (host) loss; raises PoisonedStepError after
        ``nan_patience`` consecutive non-finite values.  The driver loop
        calls this at log points — loss observation already forces a
        device sync there, so the check adds no extra transfer."""
        self._started = None  # the step chain up to here completed
        if math.isfinite(loss):
            self._nan_streak = 0
            return
        self._nan_streak += 1
        log.warning("non-finite loss %s at step %d (%d/%d before poisoned)",
                    loss, step, self._nan_streak, self.nan_patience)
        if self._nan_streak >= self.nan_patience:
            self._nan_streak = 0
            raise PoisonedStepError(
                f"loss non-finite for {self.nan_patience} consecutive "
                f"observations (last step {step})")

    def hung(self, now: Optional[float] = None) -> bool:
        if self._started is None:
            return False
        now = self._clock() if now is None else now
        return (now - self._started) > self.step_timeout_s

    def check(self) -> bool:
        """One poll: logs (and calls ``on_hang``) the first time a hang is
        seen; returns whether the current step is hung."""
        if not self.hung():
            return False
        if not self._hang_reported:
            self._hang_reported = True
            stuck_for = self._clock() - (self._started or 0.0)
            log.error("step %d appears HUNG (%.0fs > %.0fs timeout)",
                      self._step, stuck_for, self.step_timeout_s)
            if self.on_hang is not None:
                self.on_hang(self._step, stuck_for)
        return True

    def start(self, poll_interval_s: float = 5.0) -> "StepWatchdog":
        def run():
            while not self._stop.wait(poll_interval_s):
                self.check()

        self._thread = threading.Thread(
            target=run, name="bigdl-tpu-watchdog", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=6)
            self._thread = None
