"""Cluster membership — epoch-numbered views over a shared control channel.

Reference analog (unverified — mount empty): the reference's cluster
membership IS Spark's: the driver tracks executor liveness and reschedules
work, and "BigDL 2.0" (arXiv 2204.01715) presents transparent failure
recovery as a property inherited from that control plane.  The TPU
multi-controller world has no driver, so membership must be peer-agreed
state.  This module is the agreement substrate: a **view board** over a
shared directory (local, or ``gs://…`` through the ``utils.storage`` seam —
the same visibility requirement sharded checkpoints and heartbeats already
impose).

The protocol is deliberately primitive — files, not Paxos:

- A **view** is an epoch-numbered membership snapshot
  (:class:`MembershipView`): the sorted live process indices, the leader
  (always the LOWEST live rank — deterministic, no election rounds), the
  publishing step, and a reason.  The leader writes ``view-<epoch>.json``;
  everyone else adopts the highest epoch they can read.  Two processes
  that disagree about who leads (a partition) may both publish the same
  epoch; last-write-wins, and the disagreement is transient because the
  leader rule is a pure function of the live set.
- An **abort flag** (``abort-<epoch>.json``) is scoped to the view it
  aborts: any member may post it, every member's next step-edge check sees
  it, and it dies with the epoch — recovery publishes a new view, so a
  stale flag can never re-abort a recovered gang.
- A **preemption notice** (``preempt-<epoch>-r<rank>.json``) propagates a
  local SIGTERM cluster-wide, also epoch-scoped: the signalled host posts
  it, every other host treats it as its own preemption and takes the
  just-in-time checkpoint too (a maintenance event that takes one host of
  a gang takes the GANG).
- A **rendezvous ack** (``ack-<epoch>-r<rank>.json``) is the barrier
  primitive gang recovery uses: survivors ack the new view's epoch and
  wait until every member of that view has acked before re-entering
  training together.

Everything routes through ``utils.storage``, so ``memory://`` gives tests
real remote semantics with no network and no sleeps (clocks are
injectable one layer up, in :mod:`.cluster`).
"""

import json
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional, Tuple

from bigdl_tpu.utils import storage
from bigdl_tpu.utils.log import get_logger

log = get_logger("bigdl_tpu.resilience")


@dataclass(frozen=True)
class MembershipView:
    """One epoch of agreed membership.  ``leader`` is redundant with
    ``min(members)`` but recorded so a postmortem dump is self-contained;
    ``topology`` carries the publisher's device-topology fingerprint
    (``runtime.mesh.mesh_fingerprint``) so a rejoining process on
    different hardware is detectable before it wedges a collective."""

    epoch: int
    members: Tuple[int, ...]
    leader: int
    step: int = 0
    reason: str = "initial"
    preempt: bool = False
    topology: str = ""
    published_by: int = -1
    time: float = 0.0

    def to_dict(self) -> Dict:
        d = asdict(self)
        d["members"] = list(self.members)
        return d

    @staticmethod
    def from_dict(d: Dict) -> "MembershipView":
        return MembershipView(
            epoch=int(d["epoch"]), members=tuple(int(m) for m in d["members"]),
            leader=int(d["leader"]), step=int(d.get("step", 0)),
            reason=str(d.get("reason", "")), preempt=bool(d.get("preempt")),
            topology=str(d.get("topology", "")),
            published_by=int(d.get("published_by", -1)),
            time=float(d.get("time", 0.0)))


def _view_name(epoch: int) -> str:
    return f"view-{epoch:06d}.json"


def _abort_name(epoch: int) -> str:
    return f"abort-{epoch:06d}.json"


def _preempt_name(epoch: int, rank: int) -> str:
    return f"preempt-{epoch:06d}-r{rank:05d}.json"


def _ack_name(epoch: int, rank: int) -> str:
    return f"ack-{epoch:06d}-r{rank:05d}.json"


class MembershipBoard:
    """The shared-directory view board.  Every method is a small number of
    storage calls (one listing, or one read/write) — callers own the
    cadence (the coordinator polls at bundle edges and heartbeat sweeps,
    never per training step)."""

    def __init__(self, directory: str):
        self.directory = directory
        storage.makedirs(directory)

    # -- views --------------------------------------------------------------
    def publish(self, view: MembershipView) -> None:
        storage.write_json(
            storage.join(self.directory, _view_name(view.epoch)),
            view.to_dict())

    def current(self) -> Optional[MembershipView]:
        """The highest-epoch readable view; a torn/unreadable file is
        skipped (the previous epoch still governs) rather than crashing
        the sweep."""
        best = None
        for name in self._names():
            if not (name.startswith("view-") and name.endswith(".json")):
                continue
            try:
                epoch = int(name[len("view-"):-len(".json")])
            except ValueError:
                continue
            if best is not None and epoch <= best.epoch:
                continue
            try:
                view = MembershipView.from_dict(storage.read_json(
                    storage.join(self.directory, name)))
            except (OSError, ValueError, KeyError, json.JSONDecodeError):
                continue
            best = view
        return best

    # -- abort flags --------------------------------------------------------
    def post_abort(self, epoch: int, rank: int, reason: str,
                   step: Optional[int] = None) -> None:
        path = storage.join(self.directory, _abort_name(epoch))
        if storage.exists(path):
            return  # first abort wins; a second poster changes nothing
        storage.write_json(path, {"epoch": epoch, "rank": rank,
                                  "reason": reason, "step": step})

    def abort_posted(self, epoch: int) -> Optional[Dict]:
        path = storage.join(self.directory, _abort_name(epoch))
        try:
            if not storage.exists(path):
                return None
            return storage.read_json(path)
        except (OSError, ValueError, json.JSONDecodeError):
            return None  # torn write: the next check sees the final file

    # -- preemption notices -------------------------------------------------
    def post_preempt(self, epoch: int, rank: int) -> None:
        path = storage.join(self.directory, _preempt_name(epoch, rank))
        if not storage.exists(path):
            storage.write_json(path, {"epoch": epoch, "rank": rank})

    def preempt_posted(self, epoch: int) -> List[int]:
        """Ranks that posted a preemption notice under this epoch."""
        prefix = f"preempt-{epoch:06d}-r"
        out = []
        for name in self._names():
            if name.startswith(prefix) and name.endswith(".json"):
                try:
                    out.append(int(name[len(prefix):-len(".json")]))
                except ValueError:
                    continue
        return sorted(out)

    # -- rendezvous acks ----------------------------------------------------
    def ack(self, epoch: int, rank: int) -> None:
        storage.write_json(
            storage.join(self.directory, _ack_name(epoch, rank)),
            {"epoch": epoch, "rank": rank})

    def acks(self, epoch: int) -> List[int]:
        prefix = f"ack-{epoch:06d}-r"
        out = []
        for name in self._names():
            if name.startswith(prefix) and name.endswith(".json"):
                try:
                    out.append(int(name[len(prefix):-len(".json")]))
                except ValueError:
                    continue
        return sorted(out)

    # ------------------------------------------------------------------
    def gc(self, current_epoch: int, keep_epochs: int = 4) -> None:
        """Drop view/abort/preempt/ack files more than ``keep_epochs``
        behind the current epoch — the leader calls this after each
        publish so a long-running gang's control dir stays bounded.  A
        few historical views are kept for postmortems; nothing current
        is ever touched."""
        cutoff = current_epoch - keep_epochs
        if cutoff <= 0:
            return
        for name in self._names():
            stem = name.split("-", 1)
            if stem[0] not in ("view", "abort", "preempt", "ack") \
                    or len(stem) != 2 or not name.endswith(".json"):
                continue
            try:
                epoch = int(stem[1].split("-")[0].split(".")[0])
            except ValueError:
                continue
            if epoch < cutoff:
                storage.remove_tree(storage.join(self.directory, name),
                                    ignore_errors=True)

    def _names(self) -> List[str]:
        try:
            return storage.listdir(self.directory)
        except (OSError, ImportError):
            return []
