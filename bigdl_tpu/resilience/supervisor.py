"""Training supervisor — the ``DistriOptimizer.optimize()`` retry loop,
rebuilt as a layer OVER the driver instead of a branch inside it.

Reference analog (unverified — mount empty): ``DistriOptimizer.optimize()``
catches a failed iteration, reloads the last checkpoint and retries up to
``bigdl.failure.retryTimes`` ("BigDL 2.0", arXiv 2204.01715, names this
transparent failure recovery as a Spark-control-plane headline).  The
Optimizer here keeps its cheap IN-RUN retry (same process, device state
restorable from checkpoint); the Supervisor adds what that loop cannot do:

- survive failures that escape ``optimize()`` entirely (exhausted in-run
  retries, failures during resume itself, process-level errors surfaced
  by a restarted run),
- classify the cause (:func:`~.retry.classify`) and apply a PER-CAUSE
  retry policy — transient storage retries hard, a poisoned batch barely,
  a topology change not at all (it resumes elastically instead),
- re-enter ``optimize()`` from scratch, which REBUILDS the step engine and
  reloads the newest FULLY-VALIDATED checkpoint (``latest_checkpoint``
  accepts only shard-complete directories — a manifest alone certifies
  nothing in async sharded mode),
- account every recovery in ``Metrics`` counters: ``recoveries_total``,
  ``retries_by_cause.<cause>``, ``time_lost_to_recovery_s``.

Elastic resume: the driver records ``process_count`` in checkpoint driver
state; ``Optimizer._try_resume`` detects a mismatch at load and falls back
to replay-from-epoch-start with an explicit warning (the per-process batch
plan is keyed by process_count, so a mid-epoch skip computed under N
processes is meaningless under M).  The supervisor just guarantees the
resume happens from a restorable checkpoint.
"""

import threading
import time
from typing import Dict, Optional

from bigdl_tpu.obs import flight, trace
from bigdl_tpu.resilience import faults
from bigdl_tpu.resilience.detector import (Heartbeat, HeartbeatMonitor,
                                           StepWatchdog)
from bigdl_tpu.resilience.retry import (FailureCause, FailurePolicy,
                                        classify)
from bigdl_tpu.utils.log import get_logger

log = get_logger("bigdl_tpu.resilience")


class Supervisor:
    """Wraps an :class:`~bigdl_tpu.optim.optimizer.Optimizer`; ``run()``
    returns what ``optimize()`` would, surviving what it would not."""

    def __init__(self, optimizer, policy: Optional[FailurePolicy] = None,
                 sleep=time.sleep):
        self.optimizer = optimizer
        if policy is None:
            from bigdl_tpu.runtime.engine import Engine

            policy = Engine.get().config.resolved_failure_policy()
        self.policy = policy
        if getattr(optimizer, "failure_policy", None) is None:
            # the driver's in-run retry loop must enforce the same
            # per-cause bounds as the supervision loop around it
            optimizer.failure_policy = policy
        self.metrics = optimizer.metrics
        self._sleep = sleep
        self.restarts_total = 0
        self._by_cause: Dict[FailureCause, int] = {}

    # -- the supervision loop ----------------------------------------------
    def run(self):
        policy = self.policy
        heartbeat = monitor_stop = None
        own_cluster = False
        if policy.cluster_dir \
                and getattr(self.optimizer, "cluster", None) is None:
            # the full control plane (docs/resilience.md §Multi-host
            # recovery): membership views + gang recovery + peer-shard
            # restore.  The coordinator beats and monitors itself, so the
            # plain heartbeat_dir path below is skipped — running both
            # would double-count every suspicion episode.
            from bigdl_tpu.resilience.cluster import (ClusterConfig,
                                                      ClusterCoordinator)

            coord = ClusterCoordinator(ClusterConfig(
                directory=policy.cluster_dir,
                heartbeat_interval_s=policy.heartbeat_interval_s,
                phi_threshold=policy.heartbeat_phi_threshold,
                rendezvous_timeout_s=policy.cluster_rendezvous_timeout_s),
                metrics=self.metrics)
            coord.start(background=True)
            self.optimizer.cluster = coord
            own_cluster = True
        if policy.heartbeat_dir \
                and getattr(self.optimizer, "cluster", None) is None:
            heartbeat = Heartbeat(
                policy.heartbeat_dir,
                interval_s=policy.heartbeat_interval_s).start()
            monitor_stop = self._start_peer_monitor(policy)
        if getattr(self.optimizer, "watchdog", None) is None:
            self.optimizer.watchdog = StepWatchdog(
                step_timeout_s=policy.watchdog_step_timeout_s,
                nan_patience=policy.nan_patience)
        # the watchdog's hang half only works if something POLLS it; the
        # driver thread is the one that may be wedged in XLA, so polling
        # runs on the watchdog's own background thread
        own_watchdog_thread = self.optimizer.watchdog._thread is None
        if own_watchdog_thread:
            self.optimizer.watchdog.start(
                poll_interval_s=max(1.0, min(
                    30.0, policy.watchdog_step_timeout_s / 4)))
        try:
            while True:
                t0 = time.perf_counter()
                try:
                    return self.optimizer.optimize()
                except (KeyboardInterrupt, SystemExit):
                    raise
                except Exception as e:
                    self._recover_or_raise(e, time.perf_counter() - t0)
        finally:
            if own_watchdog_thread:
                self.optimizer.watchdog.stop()
            if monitor_stop is not None:
                monitor_stop.set()
            if heartbeat is not None:
                heartbeat.stop()
            if own_cluster:
                self.optimizer.cluster.stop()
                self.optimizer.cluster = None

    def _start_peer_monitor(self, policy) -> threading.Event:
        """Background phi-accrual sweep over the peers' heartbeats: a peer
        crossing ``heartbeat_phi_threshold`` is logged (once per episode)
        and counted — the observability half of liveness; acting on it
        (restart/elastic resume) belongs to the process manager."""
        monitor = HeartbeatMonitor(policy.heartbeat_dir)
        stop = threading.Event()
        suspected = set()

        def sweep():
            while not stop.wait(policy.heartbeat_interval_s):
                try:
                    now_suspect = set(monitor.suspects(
                        threshold=policy.heartbeat_phi_threshold))
                except Exception as e:  # shared dir blip: retry next sweep
                    log.warning("heartbeat sweep failed: %s", e)
                    continue
                for idx in sorted(now_suspect - suspected):
                    log.error("peer process %d SUSPECTED dead "
                              "(phi > %.1f)", idx,
                              policy.heartbeat_phi_threshold)
                    self.metrics.inc("peers_suspected_total")
                    flight.record("peer_suspected", process=idx)
                for idx in sorted(suspected - now_suspect):
                    log.info("peer process %d recovered", idx)
                suspected.clear()
                suspected.update(now_suspect)

        threading.Thread(target=sweep, name="bigdl-tpu-hb-monitor",
                         daemon=True).start()
        return stop

    def _recover_or_raise(self, exc: Exception, run_time_s: float) -> None:
        """Account the failure; raise when the policy is exhausted or the
        restart could not be made safe; otherwise sleep the backoff and
        let the loop re-enter ``optimize()``."""
        cause = classify(exc)
        retry_policy = self.policy.policy_for(cause)
        self.restarts_total += 1
        attempt = self._by_cause[cause] = self._by_cause.get(cause, 0) + 1
        if self.restarts_total > self.policy.max_restarts:
            log.error("supervisor: restart budget exhausted (%d); giving up",
                      self.policy.max_restarts)
            raise exc
        if attempt > retry_policy.max_retries \
                and cause is not FailureCause.TOPOLOGY_CHANGE:
            log.error("supervisor: %s retries exhausted (%d); giving up",
                      cause.value, retry_policy.max_retries)
            raise exc
        t_rec = time.perf_counter()
        with trace.span("resilience/recover", cause=cause.value,
                        attempt=attempt):
            if not self._restartable():
                raise exc
            self.metrics.inc("recoveries_total")
            self.metrics.inc(f"retries_by_cause.{cause.value}")
            flight.record(
                "supervisor_restart", cause=cause.value, attempt=attempt,
                restarts_total=self.restarts_total, run_time_s=run_time_s,
                error=f"{type(exc).__name__}: {exc}")
            delay = retry_policy.backoff(attempt)
            log.warning(
                "supervisor: run failed after %.1fs (%s: %s); restart %d/%d "
                "[cause %s, attempt %d] in %.2fs",
                run_time_s, type(exc).__name__, exc, self.restarts_total,
                self.policy.max_restarts, cause.value, attempt, delay)
            self._sleep(delay)
            coord = getattr(self.optimizer, "cluster", None)
            if coord is not None:
                # a restart that escaped optimize() rewinds this process's
                # device state, so the whole gang must rewind WITH it:
                # abort the current view's collectives, rendezvous on the
                # next view, and only then re-enter optimize() together
                coord.gang_recover(cause.value)
                coord.note_recovered(time.perf_counter() - t_rec)
        # only handler + backoff time counts as lost — most of the failed
        # run's progress survives in checkpoints (the in-run retry path
        # accounts the same way); the full run_time_s is in the log line
        self.metrics.inc("time_lost_to_recovery_s",
                         time.perf_counter() - t_rec)

    def _restartable(self) -> bool:
        """A restart is safe when a shard-complete checkpoint exists to
        resume from, or the policy allows a from-scratch restart."""
        from bigdl_tpu.optim import checkpoint as ckpt

        opt = self.optimizer
        path = getattr(opt, "_ckpt_path", None)
        if path:
            # an in-flight async write may BE the newest checkpoint
            try:
                opt._ckpt_drain(raise_error=False)
            except Exception:  # pragma: no cover — drain is best-effort
                pass
            latest = ckpt.latest_checkpoint(path)
            if latest is not None:
                log.info("supervisor: will resume from %s "
                         "(newest shard-complete checkpoint)", latest)
                return True
        if self.policy.restart_from_scratch:
            log.warning("supervisor: no restorable checkpoint under %r; "
                        "restarting from scratch", path)
            return True
        log.error("supervisor: no restorable checkpoint and "
                  "restart_from_scratch is disabled")
        return False


def supervise(optimizer, policy: Optional[FailurePolicy] = None):
    """One-call form: ``supervise(opt).optimize()``-equivalent —
    ``supervise(opt)`` runs the optimizer under a Supervisor and returns
    the TrainedModel."""
    return Supervisor(optimizer, policy=policy).run()
