"""Validation methods (metrics).

Reference analog (unverified — mount empty): ``dllib/optim/ValidationMethod.
scala`` — ``Top1Accuracy``, ``Top5Accuracy``, ``Loss``, ``MAE``, ``TreeNN...``
returning ``ValidationResult``s that fold with ``+``.  TPU-native: each method
maps (output, target) -> (sum, count) inside the jitted eval step; sums are
``psum``-reduced over the mesh and accumulated across batches ON DEVICE
(async scalar adds) — one device→host sync per validation run, never a
blocking float per batch (``ShardedParameterStep.evaluate``).
"""

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


class ValidationResult:
    def __init__(self, sum_: float, count: float, name: str):
        self.sum = float(sum_)
        self.count = float(count)
        self.name = name

    @property
    def result(self) -> float:
        return self.sum / max(self.count, 1e-12)

    def __add__(self, other: "ValidationResult") -> "ValidationResult":
        return ValidationResult(self.sum + other.sum, self.count + other.count,
                                self.name)

    def __repr__(self):
        return f"{self.name}: {self.result:.6f} ({int(self.count)} samples)"


class ValidationMethod:
    name = "metric"

    def batch_stats(self, output, target, weight=None
                    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """(sum, count) for one batch — pure, runs inside jit.  ``weight`` is
        a per-sample 0/1 (or fractional) weight; padded rows carry 0."""
        raise NotImplementedError

    def fold(self, sum_, count) -> ValidationResult:
        return ValidationResult(sum_, count, self.name)


class StatsAccumulator:
    """Accumulates per-method ``(sum, count)`` pairs ON DEVICE across
    batches (tiny async adds); ``fetch()`` syncs once per validation run.
    Forcing a host float per batch would serialize the whole run on
    device→host transfers."""

    def __init__(self):
        self.totals = None

    def add(self, stats) -> None:
        if self.totals is None:
            self.totals = [(s, c) for s, c in stats]
        else:
            self.totals = [(a + s, b + c)
                           for (a, b), (s, c) in zip(self.totals, stats)]

    def fetch(self) -> Optional[list]:
        """One ``jax.device_get`` of everything; ``None`` if no batches."""
        if self.totals is None:
            return None
        return [(float(s), float(c)) for s, c in jax.device_get(self.totals)]


def _w(weight, batch: int):
    return jnp.ones((batch,), jnp.float32) if weight is None else weight


def _class_target(output, target):
    """Accept integer labels OR one-hot/soft targets (argmax them), matching
    CrossEntropyCriterion's target handling."""
    if (target.ndim == output.ndim and target.shape == output.shape
            and jnp.issubdtype(target.dtype, jnp.floating)):
        return jnp.argmax(target, axis=-1)
    return target.astype(jnp.int32)


class Top1Accuracy(ValidationMethod):
    name = "Top1Accuracy"

    def batch_stats(self, output, target, weight=None):
        pred = jnp.argmax(output, axis=-1)
        tgt = _class_target(output, target).reshape(pred.shape)
        hits = (pred == tgt).astype(jnp.float32).reshape(pred.shape[0], -1)
        w = _w(weight, pred.shape[0])
        return jnp.sum(hits * w[:, None]), jnp.sum(w) * hits.shape[1]


class Top5Accuracy(ValidationMethod):
    name = "Top5Accuracy"

    def batch_stats(self, output, target, weight=None):
        if output.shape[-1] <= 5:
            raise ValueError(
                f"Top5Accuracy is degenerate with {output.shape[-1]} classes "
                "(always 1.0); use Top1Accuracy")
        _, top5 = jax.lax.top_k(output, 5)
        tgt = _class_target(output, target).reshape(
            output.shape[:-1])[..., None]
        hits = jnp.any(top5 == tgt, axis=-1).astype(jnp.float32).reshape(
            output.shape[0], -1)
        w = _w(weight, output.shape[0])
        return jnp.sum(hits * w[:, None]), jnp.sum(w) * hits.shape[1]


class Loss(ValidationMethod):
    """Average criterion value — reference ``Loss(criterion)``."""

    name = "Loss"

    def __init__(self, criterion=None):
        from bigdl_tpu.nn.criterion import CrossEntropyCriterion

        self.criterion = criterion or CrossEntropyCriterion()

    def batch_stats(self, output, target, weight=None):
        if weight is None:
            n = jnp.asarray(output.shape[0], jnp.float32)
            return self.criterion(output, target) * n, n
        # per-sample weighting: evaluate the criterion per-row.  Uses the
        # criterion on singleton batches via vmap to respect arbitrary losses.
        per = jax.vmap(lambda o, t: self.criterion(o[None], t[None]))(
            output, target)
        return jnp.sum(per * weight), jnp.sum(weight)


class MAE(ValidationMethod):
    name = "MAE"

    def batch_stats(self, output, target, weight=None):
        per = jnp.mean(jnp.abs(output - target).reshape(output.shape[0], -1),
                       axis=-1)
        w = _w(weight, output.shape[0])
        return jnp.sum(per * w), jnp.sum(w)


class MSE(ValidationMethod):
    name = "MSE"

    def batch_stats(self, output, target, weight=None):
        per = jnp.mean(((output - target) ** 2).reshape(output.shape[0], -1),
                       axis=-1)
        w = _w(weight, output.shape[0])
        return jnp.sum(per * w), jnp.sum(w)


def _rank_of_positive(output, target):
    """Rank of the positive candidate with half-credit ties (matches AUC's
    tie handling — a constant-score model ranks mid-pack, not first).  A NaN
    positive score ranks LAST: every NaN comparison is false, which would
    otherwise make a diverged model look perfect."""
    tgt = target.astype(jnp.int32).reshape(output.shape[0])
    pos = jnp.take_along_axis(output, tgt[:, None], axis=-1)
    greater = jnp.sum((output > pos).astype(jnp.float32), axis=-1)
    ties = jnp.sum((output == pos).astype(jnp.float32), axis=-1) - 1.0
    rank = greater + 0.5 * ties
    bad = jnp.isnan(pos[:, 0]) | jnp.any(jnp.isnan(output), axis=-1)
    return jnp.where(bad, jnp.asarray(output.shape[-1], rank.dtype), rank)


class Precision(ValidationMethod):
    """Per-class precision TP / predicted-positive (default: class 1, the
    binary-positive convention)."""

    name = "Precision"

    def __init__(self, positive_class: int = 1):
        self.cls = positive_class

    def batch_stats(self, output, target, weight=None):
        pred = jnp.argmax(output, axis=-1).reshape(-1)
        tgt = _class_target(output, target).reshape(pred.shape)
        w = _w(weight, pred.shape[0])
        pp = (pred == self.cls).astype(jnp.float32) * w
        tp = pp * (tgt == self.cls)
        return jnp.sum(tp), jnp.sum(pp)


class Recall(ValidationMethod):
    """Per-class recall TP / actual-positive (default: class 1)."""

    name = "Recall"

    def __init__(self, positive_class: int = 1):
        self.cls = positive_class

    def batch_stats(self, output, target, weight=None):
        pred = jnp.argmax(output, axis=-1).reshape(-1)
        tgt = _class_target(output, target).reshape(pred.shape)
        w = _w(weight, pred.shape[0])
        ap = (tgt == self.cls).astype(jnp.float32) * w
        tp = ap * (pred == self.cls)
        return jnp.sum(tp), jnp.sum(ap)


class HitRatio(ValidationMethod):
    """HR@k over candidate scores — reference ``optim/ValidationMethod.scala``
    ``HitRatio(k, negNum)`` (recsys eval: did the positive item rank in the
    top-k among its negatives).

    Here ``output`` is (N, n_candidates) scores and ``target`` the index of
    the positive candidate per row (0-based)."""

    def __init__(self, k: int = 10):
        self.k = k
        self.name = f"HitRatio@{k}"

    def batch_stats(self, output, target, weight=None):
        rank = _rank_of_positive(output, target)
        hits = (rank < self.k).astype(jnp.float32)
        w = _w(weight, output.shape[0])
        return jnp.sum(hits * w), jnp.sum(w)


class NDCG(ValidationMethod):
    """NDCG@k with a single positive per row — reference ``NDCG`` validation
    method.  Same (scores, positive-index) convention as :class:`HitRatio`;
    with one relevant item the ideal DCG is 1, so NDCG = 1/log2(rank+2) when
    the positive ranks inside the top-k, else 0."""

    def __init__(self, k: int = 10):
        self.k = k
        self.name = f"NDCG@{k}"

    def batch_stats(self, output, target, weight=None):
        rank = _rank_of_positive(output, target)
        gain = jnp.where(rank < self.k, 1.0 / jnp.log2(rank + 2.0), 0.0)
        w = _w(weight, output.shape[0])
        return jnp.sum(gain * w), jnp.sum(w)


class AUC(ValidationMethod):
    """Batchwise ROC-AUC (Mann-Whitney U) for binary targets.

    The reference's ``AUC`` accumulates a global threshold curve; a (sum,
    count) fold can't express that exactly, so this computes the exact AUC
    *per batch* and averages weighted by the number of pos-neg pairs —
    identical to the global AUC when batches are iid samples, and exact
    whenever validation runs in a single batch."""

    name = "AUC"

    def batch_stats(self, output, target, weight=None):
        score = output.reshape(output.shape[0], -1)
        if score.shape[1] == 2:
            # 2-class output: rank by the positive-vs-negative margin, which
            # is monotonic in p1 for both logits and probabilities (the raw
            # last column is NOT monotonic for logits)
            score = score[:, 1] - score[:, 0]
        else:
            score = score[:, -1]  # prob/logit of positive class (sole column)
        t = target.reshape(-1).astype(jnp.float32)
        w = _w(weight, output.shape[0])
        pos = (t > 0.5).astype(jnp.float32) * w
        neg = (t <= 0.5).astype(jnp.float32) * w
        # pairwise wins + half-ties; O(batch²) but validation batches are small
        s_i = score[:, None]
        s_j = score[None, :]
        wins = (s_i > s_j).astype(jnp.float32) + 0.5 * (s_i == s_j)
        pair_w = pos[:, None] * neg[None, :]
        u = jnp.sum(wins * pair_w)
        n_pairs = jnp.sum(pair_w)
        return u, n_pairs
