"""Checkpoint save/load.

Reference analog (unverified — mount empty): ``Optimizer.setCheckpoint(path,
trigger)`` saving ``model.<iter>`` / ``optimMethod.<iter>`` via Java
serialization (``dllib/utils/File.scala``), reloaded by the driver retry loop.

TPU-native: step-tagged directories with npz blobs + a JSON manifest.  Flat
params are replicated so process 0 writes them; the sharded optimizer state is
gathered before write (cheap relative to training; an Orbax-style per-host
sharded write is the planned optimization for pod scale).

``path`` may be local OR a remote URI (``gs://…`` via fsspec+gcsfs — the
reference's ``Optimizer.setCheckpoint`` takes an HDFS URI the same way,
``utils/File.scala``).  Atomicity differs by backend: local uses
write-tmp-then-rename; object stores have no atomic rename, so remote
writes order the manifest LAST and readers treat a ``ckpt-<step>``
prefix without a manifest as not-a-checkpoint.
"""

import os
import shutil
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from bigdl_tpu.obs import trace
from bigdl_tpu.resilience import faults
from bigdl_tpu.resilience.retry import RetryPolicy
from bigdl_tpu.utils import storage
from bigdl_tpu.utils.log import get_logger

log = get_logger("bigdl_tpu.checkpoint")

# manifest reads during checkpoint scans ride out storage blips instead of
# the old ad-hoc warn-and-skip alone: two quick retries, then skip visibly
_MANIFEST_RETRY = RetryPolicy(max_retries=2, base_s=0.05, max_s=0.2,
                              jitter=0.0)


def _path_key(path) -> str:
    """One flat string per pytree path — the npz key convention shared by
    every save/load/shard function in this module."""
    return "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                    for p in path)


def _flatten_with_paths(tree) -> Dict[str, np.ndarray]:
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        flat[_path_key(path)] = np.asarray(leaf)
    return flat


def _unflatten_like(template, flat: Dict[str, np.ndarray]):
    paths, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    for path, leaf in paths:
        arr = flat[_path_key(path)]
        leaves.append(arr.astype(np.asarray(leaf).dtype).reshape(np.asarray(leaf).shape))
    return jax.tree_util.tree_unflatten(treedef, leaves)


def jsonable_state(driver_state: Optional[Dict[str, Any]]
                   ) -> Dict[str, Any]:
    """The JSON-safe subset of a driver-state dict (scalars, nested
    scalar dicts e.g. ``schedule_state``, and nested lists e.g. the
    block-sparse FFN masks) — what a manifest or a peer-shard meta record
    may carry."""
    def ok(v):
        if isinstance(v, (int, float, str, bool)) or v is None:
            return True
        if isinstance(v, dict):
            return all(ok(x) for x in v.values())
        if isinstance(v, (list, tuple)):
            return all(ok(x) for x in v)
        return False

    return {k: v for k, v in (driver_state or {}).items() if ok(v)}


def local_opt_shards(tree) -> Dict[str, np.ndarray]:
    """Flatten a (device-resident, possibly ZeRO-sharded) optimizer-state
    pytree into THIS process's contribution: for each 1-D sharded leaf,
    the contiguous local slice plus its global offset (``<key>@offset``);
    replicated leaves (scalars, non-elementwise state) are included whole.
    The per-process cost is O(state/process_count) device→host copies —
    no cross-host allgather, unlike :func:`~..train_step.host_fetch`."""
    flat: Dict[str, np.ndarray] = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = _path_key(path)
        is_sharded = (
            isinstance(leaf, jax.Array) and leaf.ndim >= 1
            and not leaf.is_fully_replicated)
        if not is_sharded:
            flat[key] = np.asarray(leaf)
            continue
        parts = {}
        for s in leaf.addressable_shards:
            if leaf.ndim > 1:
                # same-start dedup below treats equal leading offsets as
                # replicas, which only holds when trailing dims are NOT
                # sharded — a non-leading-axis sharding would silently
                # collapse distinct slices and fail at load far away
                for d, idx in enumerate(s.index[1:], start=1):
                    if (idx.start or 0) != 0 or (
                            idx.stop is not None
                            and idx.stop != leaf.shape[d]):
                        raise ValueError(
                            f"{key}: sharded along non-leading axis {d} "
                            f"(shard index {s.index}); local_opt_shards "
                            "supports leading-axis (ZeRO) sharding only")
            start = s.index[0].start or 0
            if start not in parts:  # replicas across model axes: keep one
                parts[start] = np.asarray(s.data)
        starts = sorted(parts)
        pos = starts[0]
        for st in starts:  # the local slices must tile contiguously
            if st != pos:
                raise ValueError(
                    f"non-contiguous local shards for {key}: {starts}")
            pos += len(parts[st])
        flat[key] = np.concatenate([parts[s] for s in starts])
        flat[key + "@offset"] = np.asarray(starts[0], np.int64)
    return flat


def save_checkpoint(path: str, step: int, *, flat_params=None,
                    opt_state=None, model_state=None,
                    driver_state: Optional[Dict[str, Any]] = None,
                    keep_last: int = 3, ema_flat=None,
                    opt_shards: Optional[Dict[str, np.ndarray]] = None,
                    shard_index: int = 0, shard_count: int = 1,
                    barrier=None, attempt: Optional[str] = None,
                    mirror: Optional[str] = None) -> str:
    """Write checkpoint dir ``<path>/ckpt-<step>``; returns the dir.

    Default (``opt_shards=None``): process 0 writes everything (the
    optimizer state must already be gathered to host).

    Sharded mode (``opt_shards`` from :func:`local_opt_shards`): EVERY
    process calls this and writes only its own
    ``opt_state.shard<k>-of-<n>.npz`` — the pod-scale posture: checkpoint
    traffic per host is 1/n of the optimizer state and no DCN allgather
    happens.  ``barrier`` (e.g. ``multihost_utils.sync_global_devices``)
    runs after the shard writes so process 0's manifest — always written
    LAST — certifies that every shard landed.  Requires a path visible to
    all processes (``gs://…`` or shared/local-per-test filesystem).

    ``attempt``: a token shared by all writers of ONE save (the Optimizer
    broadcasts a uuid from process 0 on the main thread).  It lands in
    the shard filenames and the manifest, so a manifest can never certify
    a stale shard left by a previous crashed attempt at the same step —
    the freshness guarantee barriers would otherwise provide, made safe
    for the unbarriered async path.  ``None`` (unit tests, single
    writer) falls back to presence-only certification."""
    sharded = opt_shards is not None
    if not sharded and jax.process_index() != 0:
        return ""
    with trace.span("checkpoint/save", step=step, sharded=sharded):
        d = storage.join(path, f"ckpt-{step}")
        remote = storage.is_remote(path)
        # local: write into a tmp dir, rename atomically.  remote (and the
        # multi-writer sharded mode, where a cross-host rename is impossible):
        # write blobs straight under the final prefix, manifest LAST — a crash
        # mid-write leaves a prefix without a manifest, which readers skip.
        tmp = d if (remote or sharded) else d + ".tmp"
        if (remote or sharded) and shard_index == 0 \
                and storage.exists(storage.join(d, "manifest.json")):
            # re-reaching a step (preemption loop, rerun into the same bucket):
            # the old MANIFEST must go first, or a crash mid-rewrite leaves
            # new blobs certified complete by the stale manifest.  Only the
            # manifest is removed — in unbarriered (async) sharded mode other
            # hosts may already be writing fresh shards into this prefix, and
            # a whole-tree removal would race them; stale-attempt shard files
            # are made harmless by the attempt token in the filename instead.
            storage.remove_tree(storage.join(d, "manifest.json"),
                                ignore_errors=False)
        if sharded and barrier is not None:
            barrier()  # nobody writes shards until the stale manifest is gone
        storage.makedirs(tmp)

        def _savez(name, **arrs):
            with storage.open_file(storage.join(tmp, name), "wb") as f:
                np.savez(f, **arrs)

        if sharded:
            _savez(_shard_name(shard_index, shard_count, attempt),
                   **opt_shards)
            if barrier is not None:
                barrier()  # manifest below must certify ALL shards
            if shard_index != 0:
                return d
        _savez("params.npz", flat=np.asarray(flat_params))
        if ema_flat is not None:
            _savez("ema.npz", flat=np.asarray(ema_flat))
        if not sharded:
            _savez("opt_state.npz", **_flatten_with_paths(opt_state))
        _savez("model_state.npz", **_flatten_with_paths(model_state))

        manifest = {"step": step,
                    "driver_state": jsonable_state(driver_state)}
        if sharded:
            manifest["opt_shards"] = shard_count
            if attempt is not None:
                manifest["opt_shards_attempt"] = attempt
        # injection point sits AFTER the blobs and BEFORE the manifest — the
        # worst crash position: a partial prefix (or local .tmp dir) that
        # readers and GC must treat as not-a-checkpoint
        faults.fire("checkpoint_write_fail", step=step)
        storage.write_json(storage.join(tmp, "manifest.json"), manifest)
        if tmp != d:
            if os.path.exists(d):
                shutil.rmtree(d)
            os.rename(tmp, d)
        _gc(path, keep_last)
        if mirror:
            # the off-cluster copy (docs/resilience.md): bounded
            # retry-with-backoff per blob, manifest mirrored last.  Runs
            # on the manifest writer only (shard_index!=0 returned above);
            # in unbarriered async sharded mode a laggard shard may be
            # missing from the mirror — harmless, because readers validate
            # shard completeness and skip the mirrored dir until a later
            # mirror completes it.  A mirror that fails even after retries
            # degrades to a warning: the primary checkpoint is intact.
            try:
                n = storage.mirror_tree(
                    d, storage.join(mirror, f"ckpt-{step}"))
                log.info("checkpoint mirrored to %s (%d bytes)",
                         storage.join(mirror, f"ckpt-{step}"), n)
                # the mirror root is bounded like the primary — without
                # this, a long frequent-checkpoint run accumulates every
                # checkpoint ever taken in the remote bucket
                _gc(mirror, keep_last)
            except Exception as e:
                log.warning(
                    "checkpoint mirror to %r FAILED after retries (%s: "
                    "%s); the primary checkpoint at %s is intact",
                    mirror, type(e).__name__, e, d)
        log.info("checkpoint saved: %s", d)
        return d


def _shard_name(i: int, n: int, attempt: Optional[str]) -> str:
    tok = f".{attempt}" if attempt else ""
    return f"opt_state.shard{i:05d}-of-{n:05d}{tok}.npz"


def _scan_checkpoints(path: str):
    """ONE directory listing -> [(step, name, has_manifest, complete)],
    where ``complete`` is True / False / **None for unknown** (the
    manifest exists but could not be read this scan).

    A checkpoint is COMPLETE when its manifest exists (remote writes order
    it last, so a prefix without one is a partial write; local tmp dirs
    are excluded by name) AND, for sharded checkpoints, every shard file
    of the manifest's attempt is present: in async mode shard writers are
    unbarriered, so the manifest alone cannot certify laggard shards.
    The unknown state matters: readers must not OFFER such a checkpoint,
    but GC must not DELETE it either — a transient read blip must never
    destroy restorable state."""
    if not storage.isdir(path):
        return []
    out = []
    for name in storage.listdir(path):
        if not name.startswith("ckpt-") or name.endswith(".tmp"):
            continue
        try:
            step = int(name.split("-")[1])
        except ValueError:
            continue
        mpath = storage.join(path, name, "manifest.json")
        if not storage.exists(mpath):
            out.append((step, name, False, False))
            continue
        try:
            manifest = _MANIFEST_RETRY.call(
                storage.read_json, mpath,
                describe=f"manifest read {mpath}")
        except Exception as e:
            # retries exhausted: skipped VISIBLY this scan, not
            # silently lost — and not deletable either (complete=None)
            log.warning("could not read %s (%s); skipping this "
                        "checkpoint for now", mpath, e)
            out.append((step, name, True, None))
            continue
        n = int(manifest.get("opt_shards") or 0)
        tok = manifest.get("opt_shards_attempt")
        complete = not n or all(storage.exists(storage.join(
            path, name, _shard_name(i, n, tok))) for i in range(n))
        out.append((step, name, True, complete))
    return out


def _complete_steps(path: str):
    """(step, name) for every checkpoint a reader may trust: manifest
    readable AND every shard of its attempt present."""
    return [(s, n) for s, n, _m, complete in _scan_checkpoints(path)
            if complete is True]


def latest_checkpoint(path: str) -> Optional[str]:
    steps = _complete_steps(path)
    if not steps:
        return None
    return storage.join(path, max(steps)[1])


def merge_flat_shards(shard_dicts, template) -> Dict[str, np.ndarray]:
    """Merge per-process :func:`local_opt_shards` dicts back into full
    flat arrays: offset-keyed slices land at their recorded positions,
    replicated leaves pass through (any copy works).  Works for ANY
    current process count — resharding a resumed job is free.  Shared by
    the checkpoint reader and the cluster peer-shard store
    (``resilience.cluster``), which transports the same shard format over
    its control channel."""
    full: Dict[str, np.ndarray] = {}
    tpl_flat = _flatten_with_paths(template)
    for shard in shard_dicts:
        for key, arr in shard.items():
            if key.endswith("@offset"):
                continue
            off_key = key + "@offset"
            if off_key not in shard:  # replicated leaf: any copy works
                full.setdefault(key, arr)
                continue
            if key not in full:
                full[key] = np.zeros(tpl_flat[key].shape,
                                     tpl_flat[key].dtype)
            off = int(shard[off_key])
            full[key][off:off + len(arr)] = arr
    return full


def _reassemble_opt_shards(ckpt_dir: str, n: int, template,
                           attempt: Optional[str] = None
                           ) -> Dict[str, np.ndarray]:
    """Merge ``opt_state.shard*-of-*.npz`` back into full flat arrays.
    Only the manifest's ``attempt``-token files are read — stale shards
    from a crashed earlier attempt at the same step are invisible."""
    return merge_flat_shards(
        (storage.load_npz(storage.join(ckpt_dir, _shard_name(i, n, attempt)))
         for i in range(n)), template)


def load_checkpoint(ckpt_dir: str, *, opt_state_template, model_state_template
                    ) -> Tuple[np.ndarray, Any, Any, Dict[str, Any]]:
    with trace.span("checkpoint/restore", ckpt_dir=ckpt_dir):
        manifest = storage.read_json(storage.join(ckpt_dir, "manifest.json"))
        flat = storage.load_npz(storage.join(ckpt_dir, "params.npz"))["flat"]
        ema_path = storage.join(ckpt_dir, "ema.npz")
        ema = (storage.load_npz(ema_path)["flat"]
               if storage.exists(ema_path) else None)
        n_shards = manifest.get("opt_shards")
        if n_shards:
            opt_flat = _reassemble_opt_shards(
                ckpt_dir, int(n_shards), opt_state_template,
                attempt=manifest.get("opt_shards_attempt"))
        else:
            opt_flat = storage.load_npz(storage.join(ckpt_dir, "opt_state.npz"))
        mstate_flat = storage.load_npz(storage.join(ckpt_dir, "model_state.npz"))
        opt_state = _unflatten_like(opt_state_template, opt_flat)
        model_state = _unflatten_like(model_state_template, mstate_flat)
        return flat, opt_state, model_state, manifest["driver_state"], ema


# GC grace bookkeeping: shard-incomplete dirs observed by a previous scan
# of THIS process (full dir path -> step).  See the grace comment in _gc.
_gc_incomplete_seen: Dict[str, int] = {}


def _gc(path: str, keep_last: int):
    # The keep set must count only checkpoints a READER would accept —
    # full shard validation, not manifest presence.  In async sharded
    # mode a host whose background writer keeps failing accumulates
    # manifest-present-but-shard-incomplete dirs; counting those toward
    # keep_last once deleted the older fully-complete checkpoint and left
    # NOTHING restorable (ADVICE r5 medium).  The validation costs a
    # manifest read + shard probes per dir on every save — the price of
    # never GC-ing away the only resumable state.
    scan = _scan_checkpoints(path)  # ONE listing serves every pass below
    valid = [(s, n) for s, n, _m, complete in scan if complete is True]
    if not valid:
        return  # nothing restorable: delete nothing, not even partials
    newest_valid = max(valid)[0]
    if keep_last > 0:
        keep = {name for _, name in sorted(valid)[-keep_last:]}
        keep.add(max(valid)[1])  # newest restorable dir: NEVER deleted
        for step, name, has_manifest, complete in scan:
            full = storage.join(path, name)
            if complete is True:
                _gc_incomplete_seen.pop(full, None)
            if name in keep or not has_manifest:
                continue
            if complete is None:
                # completeness UNKNOWN (manifest unreadable this scan):
                # a transient read blip must never destroy what may be
                # restorable state — leave it for a later scan
                continue
            if step >= newest_valid:
                # newer-than-newest-valid but incomplete: a write in
                # flight (async shard writers are unbarriered) — not
                # garbage yet
                continue
            if complete is False and full not in _gc_incomplete_seen:
                # grace scan for shard-INCOMPLETE dirs: a single
                # storage.exists() false-negative (object-store eventual
                # consistency) must not delete a restorable checkpoint —
                # only a dir seen incomplete by TWO scans is garbage.
                # (complete=True dirs outside the keep window need no
                # grace: deleting them is GC working as intended.)
                _gc_incomplete_seen[full] = step
                continue
            _gc_incomplete_seen.pop(full, None)
            storage.remove_tree(full, ignore_errors=True)
    # partial prefixes (crash mid-write: blobs, no manifest) are
    # invisible to readers but still occupy storage — both on object
    # stores and in local/shared sharded mode, where multi-writer
    # dirs cannot use tmp+rename; sweep any older than the newest
    # restorable step (a younger one may be a write in flight).  This
    # sweep runs even with keep_last<=0 (GC-of-history disabled): a
    # manifest-less prefix is never history, only litter.
    for step, name, has_manifest, _complete in scan:
        if not has_manifest and step < newest_valid:
            storage.remove_tree(storage.join(path, name),
                                ignore_errors=True)


import threading as _threading


class AsyncCheckpointer:
    """Overlap checkpoint WRITES with training (preemptible-slice posture:
    frequent cheap checkpoints).  The CALLER owns the host snapshot (it
    must pass host arrays — the optimizer's ``host_fetch`` also handles
    multi-host sharded state, which a plain ``device_get`` here could
    not); this class owns the background npz serialization + atomic
    rename.  One write in flight; a later submit joins the previous one
    first.

    Error policy: ONE failed background write is not a training failure —
    it is logged and remembered; ``wait(raise_error=True)`` (the
    resume/exit paths, where a missing checkpoint matters) re-raises it,
    while ``submit`` only logs and proceeds with the newer write.  But a
    STREAK of failures means checkpoints are silently not landing while
    training runs on — in sharded mode each failure also litters a
    manifest-incomplete dir — so after ``escalate_after`` consecutive
    failures ``submit`` raises instead of swallowing, which surfaces the
    condition to the driver retry loop / supervisor (ADVICE r5 medium)."""

    def __init__(self, escalate_after: int = 3):
        self._thread = None
        self._error = None
        self._last_error = None
        self.escalate_after = escalate_after
        self.consecutive_failures = 0

    def submit(self, path: str, step: int, **host_kw) -> None:
        self.wait(raise_error=False)
        if self.consecutive_failures >= self.escalate_after:
            err, self._last_error = self._last_error, None
            self.consecutive_failures = 0
            raise RuntimeError(
                f"async checkpoint writes failed {self.escalate_after} "
                "times in a row; escalating — training would otherwise "
                "run on with no restorable checkpoint landing") from err

        def run():
            try:
                save_checkpoint(path, step, **host_kw)
                self.consecutive_failures = 0
            except Exception as e:
                log.warning("async checkpoint write failed: %s", e)
                self._error = e

        self._thread = _threading.Thread(
            target=run, name="bigdl-tpu-ckpt", daemon=True)
        self._thread.start()

    def wait(self, raise_error: bool = True) -> None:
        t = self._thread
        if t is not None:
            t.join()
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            self.consecutive_failures += 1
            self._last_error = err
            if raise_error:
                raise err
