"""Checkpoint save/load.

Reference analog (unverified — mount empty): ``Optimizer.setCheckpoint(path,
trigger)`` saving ``model.<iter>`` / ``optimMethod.<iter>`` via Java
serialization (``dllib/utils/File.scala``), reloaded by the driver retry loop.

TPU-native: step-tagged directories with npz blobs + a JSON manifest.  Flat
params are replicated so process 0 writes them; the sharded optimizer state is
gathered before write (cheap relative to training; an Orbax-style per-host
sharded write is the planned optimization for pod scale).

``path`` may be local OR a remote URI (``gs://…`` via fsspec+gcsfs — the
reference's ``Optimizer.setCheckpoint`` takes an HDFS URI the same way,
``utils/File.scala``).  Atomicity differs by backend: local uses
write-tmp-then-rename; object stores have no atomic rename, so remote
writes order the manifest LAST and readers treat a ``ckpt-<step>``
prefix without a manifest as not-a-checkpoint.
"""

import os
import shutil
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from bigdl_tpu.utils import storage
from bigdl_tpu.utils.log import get_logger

log = get_logger("bigdl_tpu.checkpoint")


def _flatten_with_paths(tree) -> Dict[str, np.ndarray]:
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)
        flat[key] = np.asarray(leaf)
    return flat


def _unflatten_like(template, flat: Dict[str, np.ndarray]):
    paths, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    for path, leaf in paths:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)
        arr = flat[key]
        leaves.append(arr.astype(np.asarray(leaf).dtype).reshape(np.asarray(leaf).shape))
    return jax.tree_util.tree_unflatten(treedef, leaves)


def save_checkpoint(path: str, step: int, *, flat_params, opt_state,
                    model_state, driver_state: Dict[str, Any],
                    keep_last: int = 3, ema_flat=None) -> str:
    """Write checkpoint dir ``<path>/ckpt-<step>``; returns the dir."""
    if jax.process_index() != 0:
        return ""
    d = storage.join(path, f"ckpt-{step}")
    remote = storage.is_remote(path)
    # local: write into a tmp dir, rename atomically.  remote: write blobs
    # straight under the final prefix, manifest LAST — a crash mid-write
    # leaves a prefix without a manifest, which readers skip.
    tmp = d if remote else d + ".tmp"
    if remote and storage.exists(storage.join(d, "manifest.json")):
        # re-reaching a step (preemption loop, rerun into the same bucket):
        # the old manifest must go FIRST, or a crash mid-rewrite leaves new
        # blobs certified complete by the stale manifest
        storage.remove_tree(d, ignore_errors=False)
    storage.makedirs(tmp)

    def _savez(name, **arrs):
        with storage.open_file(storage.join(tmp, name), "wb") as f:
            np.savez(f, **arrs)

    _savez("params.npz", flat=np.asarray(flat_params))
    if ema_flat is not None:
        _savez("ema.npz", flat=np.asarray(ema_flat))
    _savez("opt_state.npz", **_flatten_with_paths(opt_state))
    _savez("model_state.npz", **_flatten_with_paths(model_state))

    def _jsonable(v):
        if isinstance(v, (int, float, str, bool)) or v is None:
            return True
        if isinstance(v, dict):  # nested scalar dicts (e.g. schedule_state)
            return all(_jsonable(x) for x in v.values())
        return False

    manifest = {"step": step, "driver_state": {
        k: v for k, v in driver_state.items() if _jsonable(v)}}
    storage.write_json(storage.join(tmp, "manifest.json"), manifest)
    if not remote:
        if os.path.exists(d):
            shutil.rmtree(d)
        os.rename(tmp, d)
    _gc(path, keep_last)
    log.info("checkpoint saved: %s", d)
    return d


def _complete_steps(path: str):
    """(step, name) for every COMPLETE checkpoint under ``path`` — one
    whose manifest exists (remote writes order it last, so a prefix
    without one is a partial write; local tmp dirs are excluded by name)."""
    if not storage.isdir(path):
        return []
    steps = []
    for name in storage.listdir(path):
        if name.startswith("ckpt-") and not name.endswith(".tmp"):
            try:
                step = int(name.split("-")[1])
            except ValueError:
                continue
            if storage.exists(storage.join(path, name, "manifest.json")):
                steps.append((step, name))
    return steps


def latest_checkpoint(path: str) -> Optional[str]:
    steps = _complete_steps(path)
    if not steps:
        return None
    return storage.join(path, max(steps)[1])


def load_checkpoint(ckpt_dir: str, *, opt_state_template, model_state_template
                    ) -> Tuple[np.ndarray, Any, Any, Dict[str, Any]]:
    manifest = storage.read_json(storage.join(ckpt_dir, "manifest.json"))
    flat = storage.load_npz(storage.join(ckpt_dir, "params.npz"))["flat"]
    ema_path = storage.join(ckpt_dir, "ema.npz")
    ema = (storage.load_npz(ema_path)["flat"]
           if storage.exists(ema_path) else None)
    opt_flat = storage.load_npz(storage.join(ckpt_dir, "opt_state.npz"))
    mstate_flat = storage.load_npz(storage.join(ckpt_dir, "model_state.npz"))
    opt_state = _unflatten_like(opt_state_template, opt_flat)
    model_state = _unflatten_like(model_state_template, mstate_flat)
    return flat, opt_state, model_state, manifest["driver_state"], ema


def _gc(path: str, keep_last: int):
    entries = _complete_steps(path)
    for _, name in sorted(entries)[:-keep_last] if keep_last > 0 else []:
        storage.remove_tree(storage.join(path, name), ignore_errors=True)
    if entries and storage.is_remote(path):
        # partial prefixes (crash mid-write: blobs, no manifest) are
        # invisible to readers but still occupy the bucket; sweep any
        # older than the newest complete step (a younger one may be a
        # write in flight right now)
        newest = max(entries)[0]
        for name in storage.listdir(path):
            if not name.startswith("ckpt-") or name.endswith(".tmp"):
                continue
            try:
                step = int(name.split("-")[1])
            except ValueError:
                continue
            if step < newest and not storage.exists(
                    storage.join(path, name, "manifest.json")):
                storage.remove_tree(storage.join(path, name),
                                    ignore_errors=True)


import threading as _threading


class AsyncCheckpointer:
    """Overlap checkpoint WRITES with training (preemptible-slice posture:
    frequent cheap checkpoints).  The CALLER owns the host snapshot (it
    must pass host arrays — the optimizer's ``host_fetch`` also handles
    multi-host sharded state, which a plain ``device_get`` here could
    not); this class owns the background npz serialization + atomic
    rename.  One write in flight; a later submit joins the previous one
    first.

    Error policy: a failed BACKGROUND write is not a training failure —
    it is logged and remembered; ``wait(raise_error=True)`` (the
    resume/exit paths, where a missing checkpoint matters) re-raises it,
    while ``submit`` only logs and proceeds with the newer write."""

    def __init__(self):
        self._thread = None
        self._error = None

    def submit(self, path: str, step: int, **host_kw) -> None:
        self.wait(raise_error=False)

        def run():
            try:
                save_checkpoint(path, step, **host_kw)
            except Exception as e:
                log.warning("async checkpoint write failed: %s", e)
                self._error = e

        self._thread = _threading.Thread(
            target=run, name="bigdl-tpu-ckpt", daemon=True)
        self._thread.start()

    def wait(self, raise_error: bool = True) -> None:
        t = self._thread
        if t is not None:
            t.join()
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            if raise_error:
                raise err
