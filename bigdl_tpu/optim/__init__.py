from bigdl_tpu.optim.optim_method import (
    OptimMethod, SGD, Adam, ParallelAdam, AdamWeightDecay, Adagrad, Adadelta,
    Adamax, RMSprop, Ftrl, LarsSGD, LBFGS,
)
from bigdl_tpu.optim.schedules import (
    LearningRateSchedule, Default, Step, MultiStep, Exponential, NaturalExp,
    Poly, Warmup, SequentialSchedule, Plateau,
    EpochStep, EpochDecay, EpochSchedule, Cosine,
)
from bigdl_tpu.optim.trigger import Trigger
from bigdl_tpu.optim.validation import (
    ValidationMethod, ValidationResult, Top1Accuracy, Top5Accuracy, Loss, MAE,
    MSE, HitRatio, NDCG, AUC, Precision, Recall,
)
from bigdl_tpu.optim.optimizer import (
    Optimizer, DistriOptimizer, LocalOptimizer, TrainedModel,
)
from bigdl_tpu.optim.train_step import GradientClipping, ShardedParameterStep
from bigdl_tpu.optim.prediction_service import PredictionService  # noqa: E402,F401
