"""Optimizer — the training driver.

Reference analog (unverified — mount empty): ``dllib/optim/Optimizer.scala``
(builder API: ``setOptimMethod/setEndWhen/setCheckpoint/setValidation``) and
``DistriOptimizer.optimize()`` (SURVEY.md §4.1 call stack): the per-iteration
loop with trigger-driven validation/checkpoint, per-iteration metrics logging,
and the **driver-side retry loop** that reloads the last checkpoint on
failure (bounded by ``bigdl.failure.retryTimes``).

TPU-native: one iteration is one XLA program (no Spark stages); the loop below
only shards host batches, dispatches the jitted step, and evaluates triggers.
Loss stays on-device between logs so iterations pipeline.
"""

import os
import time
import warnings
from typing import Any, Dict, List, Optional, Sequence

import jax
import numpy as np

from bigdl_tpu.data import pipeline as pipeline_mod
from bigdl_tpu.data.dataset import DataSet
from bigdl_tpu.data.prefetch import thread_prefetch
from bigdl_tpu.obs import attr as obs_attr
from bigdl_tpu.obs import cost as obs_cost
from bigdl_tpu.obs import flight, trace
from bigdl_tpu.optim import checkpoint as ckpt
from bigdl_tpu.optim.metrics import Metrics, SummaryWriter, Timer
from bigdl_tpu.optim.optim_method import OptimMethod, SGD
from bigdl_tpu.optim.train_step import (
    GradientClipping, ShardedParameterStep, host_fetch, put_sharded,
)
from bigdl_tpu.optim.trigger import Trigger
from bigdl_tpu.optim.validation import ValidationMethod
from bigdl_tpu.resilience import faults
from bigdl_tpu.resilience.retry import classify
from bigdl_tpu.runtime.engine import Engine
from bigdl_tpu.utils.log import get_logger

log = get_logger("bigdl_tpu.optim")


class TrainedModel:
    """Returned by ``optimize()`` — the trained module + variables, with
    predict/evaluate conveniences (reference returns the mutated Module)."""

    def __init__(self, model, variables, step_engine: ShardedParameterStep):
        self.model = model
        self.variables = variables
        self._engine = step_engine

    def predict(self, x, batch_size: int = 0) -> np.ndarray:
        run = self._engine.predict_fn()
        multi = isinstance(x, tuple)  # tuple = multi-input pack
        if multi:
            x = tuple(np.asarray(a) for a in x)
        else:
            x = np.asarray(x)
        # multi-host predict runs per-process (no mesh sharding), so padding
        # to the data-axis multiple is only needed single-process
        ndev = (self._engine.n_data_replicas
                if jax.process_count() == 1 else 1)
        n = (x[0] if multi else x).shape[0]

        def pad_to(arrs, k):
            def one(a):
                p = (-a.shape[0]) % k
                return np.concatenate([a, np.repeat(a[-1:], p, 0)]) if p else a
            return tuple(one(a) for a in arrs) if multi else one(arrs)

        if batch_size <= 0:
            return np.asarray(run(pad_to(x, ndev)))[:n]
        outs = []
        for i in range(0, n, batch_size):
            xb = (tuple(a[i:i + batch_size] for a in x) if multi
                  else x[i:i + batch_size])
            outs.append(np.asarray(run(pad_to(xb, ndev)))
                        [:min(batch_size, n - i)])
        return np.concatenate(outs)

    def evaluate(self, dataset: DataSet, methods: Sequence[ValidationMethod],
                 batch_size: int = 128):
        batches = dataset.batches(
            batch_size, shuffle=False, drop_last=False,
            process_id=jax.process_index(), process_count=jax.process_count())
        return self._engine.evaluate(list(methods), batches)

    @property
    def ema_variables(self):
        """EMA weights when the run used ``ema_decay`` (the ImageNet
        EMA-eval recipe), else None.  Evaluate them via
        ``model.apply(trained.ema_variables, x)`` or
        ``trained.set_variables(trained.ema_variables)``."""
        if getattr(self._engine, "ema_flat", None) is None:
            return None
        return self._engine.get_variables(ema=True)

    def set_variables(self, variables: Dict[str, Any]) -> None:
        """Overwrite the engine's weights/state with a loaded variables
        pytree (``Module.loadModule`` analog)."""
        import jax.numpy as jnp
        from jax.flatten_util import ravel_pytree

        eng = self._engine
        if not hasattr(eng, "flat_params"):
            # layout (GSPMD) engines own their sharded placement — they
            # re-device_put the tree under the layout's NamedShardings
            eng.set_variables(variables)
            self.variables = variables
            return
        flat, _ = ravel_pytree(variables["params"])
        if flat.shape[0] != eng.n_real:
            raise ValueError(
                f"loaded params have {flat.shape[0]} elements, model has "
                f"{eng.n_real}")
        eng.flat_params = jax.device_put(
            jnp.pad(flat, (0, eng.n_pad - eng.n_real)), eng._rep)
        eng.model_state = jax.device_put(
            variables.get("state", {}), eng._rep)
        self.variables = variables


class Optimizer:
    """Builder + driver.  Works on a 1-device mesh (the LocalOptimizer case)
    and an N-device/N-host mesh (the DistriOptimizer case) with the same
    code — mesh size is the only difference."""

    def __init__(self, model, dataset: DataSet, criterion,
                 batch_size: int = 32, seed: int = 42):
        self.model = model
        self.dataset = dataset
        self.criterion = criterion
        self.batch_size = batch_size
        self.seed = seed
        self.optim_method: OptimMethod = SGD(learning_rate=1e-2)
        self.end_when: Trigger = Trigger.max_epoch(10)
        self.clip: Optional[GradientClipping] = None
        self._ckpt_path: Optional[str] = None
        self._ckpt_trigger: Optional[Trigger] = None
        self._ckpt_sharded = "auto"
        self._ckpt_mirror = None
        self._ckpt_async = None
        self._val_trigger: Optional[Trigger] = None
        self._val_dataset: Optional[DataSet] = None
        self._val_methods: Optional[List[ValidationMethod]] = None
        self._val_batch: int = batch_size
        self._train_summary: Optional[SummaryWriter] = None
        self._val_summary: Optional[SummaryWriter] = None
        self.log_every = 1
        self.prefetch = 2  # device-transfer lookahead depth (1 = no overlap)
        self.host_prefetch = 2  # host-side producer lookahead (batches the
        #                         IO/decode producer runs ahead of dispatch).
        #                         0 = inline production — only right when
        #                         the producer is trivially cheap (in-RAM
        #                         arrays on a starved host); an IO/decode-
        #                         bound producer MUST run ahead or the
        #                         device idles every step (docs/data.md)
        self.streaming = True  # stage-parallel input pipeline when the
        #                        dataset supports it (stream_batches);
        #                        host_prefetch=0 forces inline production
        self.bf16_grads = False  # DEPRECATED: grad_comm = "bf16" spelling
        self.grad_comm = None  # gradient-sync wire format (docs/
        #                        parallelism.md §Gradient compression):
        #                        "fp32" | "bf16" | "int8" (blockwise-
        #                        quantized, ~4x fewer gradient bytes);
        #                        None = inherit EngineConfig.grad_comm
        self.comm_bucket_bytes = None  # max flat-gradient bytes per
        #                                collective (bucketed overlap);
        #                                None = EngineConfig's, which
        #                                defaults to one monolithic sync
        self.param_comm = None  # updated-param all_gather wire format:
        #                         "fp32" | "int8" (blockwise-quantized
        #                         delta gather, ~4x fewer param-gather
        #                         bytes — docs/parallelism.md);
        #                         None = fp32
        self.quant_block = None  # int8 scale granularity (elements per
        #                          f32 scale); None = collectives default
        self.remat = False       # jax.checkpoint the forward (HBM for FLOPs)
        self.remat_policy = None  # None|'nothing'|'dots' (keep MXU outputs)
        self.trainable_mask = None  # bool pytree over params (LoRA/freeze)
        self.accum_steps = 1     # gradient-accumulation microbatches
        self.ema_decay = 0.0     # weight EMA (0 = off); read the result
        #                          via TrainedModel.ema_variables
        self.seq_parallel = False  # shard dim 1 over the mesh "seq" axis
        #                            (long-context; model attention must be
        #                            seq_parallel-aware)
        self.steps_per_call = None  # fused multi-step execution (docs/
        #                             performance.md): compile K train
        #                             steps as ONE XLA program so the host
        #                             re-enters Python once per bundle, not
        #                             once per step.  int K, "auto" (pick K
        #                             from measured dispatch-vs-step time
        #                             after the first log window), or None
        #                             = inherit EngineConfig.steps_per_call
        self.metrics = Metrics()
        self.watchdog = None  # resilience.StepWatchdog (Supervisor installs
        #                       one; set directly for standalone NaN/hang
        #                       detection)
        self.cluster = None  # resilience.ClusterCoordinator (the Supervisor
        #                      installs one when FailurePolicy.cluster_dir is
        #                      set; set_cluster attaches one directly).  The
        #                      driver calls its bundle-edge hook, publishes
        #                      peer-shard state at checkpoints, and prefers
        #                      peer-shard restore in _try_resume
        self.failure_policy = None  # per-Optimizer FailurePolicy override
        #                             (Supervisor propagates its own here so
        #                             the in-run retry loop honors the same
        #                             per-cause bounds); None = engine's
        self._final_state: Optional[Dict[str, Any]] = None
        self._last_val_iter = -1
        self._last_ckpt_iter = -1
        self._preempt_signals: tuple = ()
        self._preempted = False
        self._profiler = None
        self._summary_triggers: Dict[str, Trigger] = {}
        self._last_hist_iter = -1
        # bundle runtime state (resolved per optimize() run)
        self._bundle_k = 1
        self._bundle_auto = False
        self._bundle_picked = False
        self._pending_losses: List = []  # [(first_step, loss_vec, gnorm_vec)]
        self._last_dispatch_end: Optional[float] = None
        self._inflight = 0
        # perf attribution (docs/observability.md §Step-time attribution):
        # per-window wall-time decomposition + live MFU/collective-bytes
        # accounting, resolved per optimize() run
        self.attribution: Optional[obs_attr.StepAttribution] = None
        self._attr_t0: Optional[float] = None
        self._attr_prev_it = 0
        self._attr_dispatch = 0.0
        self._attr_overhead = 0.0
        self._flops_per_step: Optional[float] = None
        self._eff_flops_per_step: Optional[float] = None
        self._peak_flops: Optional[float] = None
        self._ici_bytes_step = 0.0
        self._dcn_bytes_step = 0.0
        self._recompile: Optional[obs_attr.RecompileSentinel] = None

    # ---- builder API (reference names, snake_case) -----------------------
    def set_optim_method(self, method: OptimMethod) -> "Optimizer":
        self.optim_method = method
        return self

    def set_initial_variables(self, variables: Dict[str, Any]) -> "Optimizer":
        """Start training from the given variables pytree instead of a
        fresh ``model.init`` (fine-tuning, e.g. converted torch weights)."""
        self._initial_variables = variables
        return self

    def set_end_when(self, trigger: Trigger) -> "Optimizer":
        self.end_when = trigger
        return self

    def set_checkpoint(self, path: str, trigger: Trigger,
                       async_write: bool = False,
                       sharded="auto",
                       mirror: Optional[str] = None) -> "Optimizer":
        """``path`` may be a local directory or a remote URI (``gs://…``
        via the optional fsspec+gcsfs — the reference's
        ``setCheckpoint(hdfs://…)`` analog); a preemptible TPU VM must
        checkpoint off-VM to survive.  ``async_write=True`` snapshots to
        host at the trigger and runs the npz serialization on a
        background thread (one in flight) — the cheap-frequent-checkpoint
        posture for preemptible slices.

        ``mirror``: a second (typically remote) checkpoint root every
        completed save is copied to with bounded retry-with-backoff
        (``storage.mirror_tree``) — the off-cluster copy that survives
        the whole pod being reclaimed.  Mirror failures degrade to a
        warning after retries; the primary save already landed.

        ``sharded``: ``"auto"`` (default) writes the ZeRO-1 optimizer
        state as per-process shard files whenever the job is multi-host —
        each host writes 1/n of the state with NO cross-host allgather
        (the Orbax-style pod-scale posture; the path must be visible to
        every process, e.g. ``gs://…``).  ``False`` forces the gathered
        single-writer format; ``True`` forces sharding.  Loading
        reassembles shards for ANY process count, so resharding a resumed
        job is free."""
        self._ckpt_path = path
        self._ckpt_trigger = trigger
        self._ckpt_sharded = sharded
        self._ckpt_mirror = mirror
        self._ckpt_async = (ckpt.AsyncCheckpointer() if async_write
                            else None)
        return self

    def set_validation(self, trigger: Trigger, dataset: DataSet,
                       methods: Sequence[ValidationMethod],
                       batch_size: Optional[int] = None) -> "Optimizer":
        self._val_trigger = trigger
        self._val_dataset = dataset
        self._val_methods = list(methods)
        if batch_size:
            self._val_batch = batch_size
        return self

    def set_gradient_clipping_by_l2_norm(self, norm: float) -> "Optimizer":
        self.clip = self.clip or GradientClipping()
        self.clip.l2_norm = norm
        return self

    def set_constant_gradient_clipping(self, min_v: float, max_v: float
                                       ) -> "Optimizer":
        self.clip = self.clip or GradientClipping()
        self.clip.constant_min = min_v
        self.clip.constant_max = max_v
        return self

    def set_train_summary(self, log_dir: str) -> "Optimizer":
        self._train_summary = SummaryWriter(log_dir, "train")
        return self

    def set_summary_trigger(self, tag: str, trigger: Trigger) -> "Optimizer":
        """Opt-in heavy summary streams — reference
        ``TrainSummary.setSummaryTrigger``.  Supported tag: ``"Parameters"``
        (per-parameter histograms; costs a device→host fetch per firing,
        which is why it is trigger-gated like the reference)."""
        if tag != "Parameters":
            raise ValueError(f"unknown summary tag {tag!r} "
                             "(supported: 'Parameters')")
        self._summary_triggers[tag] = trigger
        return self

    def set_val_summary(self, log_dir: str) -> "Optimizer":
        self._val_summary = SummaryWriter(log_dir, "validation")
        return self

    def set_profile(self, log_dir: str, start_iter: int = 10,
                    num_iters: int = 5) -> "Optimizer":
        """Capture a jax.profiler trace over a warm window of iterations —
        SURVEY.md §6.1 TPU mapping of the reference's per-iteration Metrics
        dump."""
        from bigdl_tpu.utils.profiling import IterationProfiler

        self._profiler = IterationProfiler(log_dir, start_iter, num_iters)
        return self

    def set_cluster(self, coordinator) -> "Optimizer":
        """Attach a :class:`~bigdl_tpu.resilience.cluster.
        ClusterCoordinator` (docs/resilience.md §Multi-host recovery):
        membership/abort checks at every bundle edge, peer-shard
        publishes alongside every checkpoint, and peer-shard-first
        restore.  The Supervisor attaches one automatically when
        ``FailurePolicy.cluster_dir`` is set."""
        self.cluster = coordinator
        return self

    def set_preemption_checkpoint(self, *signals) -> "Optimizer":
        """Save a checkpoint and stop cleanly when the process receives a
        preemption signal (default SIGTERM — what TPU-VM maintenance events
        deliver).  SURVEY.md §6.3 TPU mapping of the reference's
        checkpoint-restart stance; requires ``set_checkpoint``."""
        import signal as _signal

        self._preempt_signals = signals or (_signal.SIGTERM,)
        return self

    def _resolved_grad_comm(self, config) -> str:
        """The run's gradient-sync wire format: the explicit
        ``grad_comm`` attribute, else the deprecated ``bf16_grads=True``
        spelling (warned, mapped to "bf16"), else the engine default."""
        if self.grad_comm is not None:
            mode = str(self.grad_comm).strip().lower()
            if self.bf16_grads and mode != "bf16":
                warnings.warn(
                    "both grad_comm and the deprecated bf16_grads are "
                    f"set; grad_comm={mode!r} wins",
                    DeprecationWarning, stacklevel=2)
            return mode
        if self.bf16_grads:
            warnings.warn(
                "Optimizer.bf16_grads is deprecated: set "
                "grad_comm='bf16' (docs/parallelism.md §Gradient "
                "compression)", DeprecationWarning, stacklevel=2)
            return "bf16"
        return getattr(config, "grad_comm", "fp32") or "fp32"

    # ---- the driver loop --------------------------------------------------
    def optimize(self) -> TrainedModel:
        engine = Engine.get()
        mesh = engine.mesh
        rng = jax.random.PRNGKey(self.seed)
        if self._profiler is None \
                and getattr(engine.config, "profile_dir", None):
            # EngineConfig.profile_dir / BIGDL_TPU_PROFILE_DIR: trace a warm
            # window without touching the builder; the finally below
            # guarantees close() even when training ends inside the window
            self.set_profile(engine.config.profile_dir)

        # init params from one sample batch
        sample = next(iter(self.dataset.batches(
            self.batch_size, shuffle=False, process_count=jax.process_count())))
        sx = sample["input"]
        init_args = tuple(np.asarray(a[:1]) for a in sx) \
            if isinstance(sx, tuple) else (np.asarray(sx[:1]),)
        init_vars = getattr(self, "_initial_variables", None) \
            or self.model.init(rng, *init_args)
        if self.trainable_mask is None:
            # keras-1 layer.trainable=False convention: derive the mask
            # automatically when any module in the tree is frozen
            from bigdl_tpu.nn.freeze import has_frozen, trainable_mask_for

            if has_frozen(self.model):
                self.trainable_mask = trainable_mask_for(
                    self.model, init_vars["params"])
        step_kw = dict(
            grad_comm=self._resolved_grad_comm(engine.config),
            comm_bucket_bytes=(self.comm_bucket_bytes
                               if self.comm_bucket_bytes is not None
                               else getattr(engine.config,
                                            "comm_bucket_bytes", None)))
        if self.quant_block is not None:
            step_kw["quant_block"] = int(self.quant_block)
        if self.param_comm is not None:
            step_kw["param_comm"] = str(self.param_comm)
        step_engine = ShardedParameterStep(
            self.model, self.criterion, self.optim_method, mesh, init_vars,
            clip=self.clip, remat=self.remat,
            remat_policy=self.remat_policy,
            trainable_mask=self.trainable_mask,
            accum_steps=self.accum_steps, ema_decay=self.ema_decay,
            seq_parallel=self.seq_parallel, **step_kw)
        n_params = step_engine.n_real
        log.info("model has %s parameters; mesh data axis = %d; ZeRO shard = %s",
                 f"{n_params:,}", step_engine.ndev,
                 f"{step_engine.shard_size:,}")
        # fused multi-step execution: per-step PRNG derives on device from
        # the step counter (no host PRNGKey/fold_in per step, even at K=1)
        step_engine.set_step_seed(self.seed + 1)
        self._arm_perf_accounting(engine, step_engine, init_vars, init_args)
        if os.environ.get("BIGDL_TPU_MEASURE_OVERLAP", "0") in ("1",
                                                                "true"):
            # opt-in startup audit (two extra compiles): how much of the
            # gradient-sync collective time hides under compute — the
            # live counterpart of bench_scaling --grad-comm
            try:
                ov = step_engine.measure_overlap(
                    step_engine.shard_batch(sample["input"]),
                    step_engine.shard_batch(
                        np.asarray(sample["target"])))
                self.metrics.gauge("train.comm_overlap_efficiency",
                                   ov["overlap_efficiency"])
                self.metrics.gauge("train.comm_exposed_collective_s",
                                   ov["exposed_collective_s"])
                flight.record("comm_overlap_audit", **ov)
            except Exception as e:  # pragma: no cover — exotic meshes
                log.warning("overlap audit failed (%s); skipped", e)
        spc = self.steps_per_call
        if spc is None:
            spc = getattr(engine.config, "steps_per_call", 1) or 1
        self._bundle_auto = spc == "auto"
        if isinstance(spc, str) and not self._bundle_auto:
            raise ValueError(
                f"steps_per_call {spc!r}: an int >= 1 or 'auto'")
        self._bundle_k = 1 if self._bundle_auto else max(1, int(spc))
        self._bundle_picked = False
        self._pending_losses = []
        self._last_dispatch_end = None
        self._inflight = 0

        state: Dict[str, Any] = {
            "epoch": 1, "iteration": 0, "epoch_batch": 0,
            "epoch_finished": False,
            "loss": float("nan"), "score": float("-inf"),
        }

        # resume if a checkpoint exists
        if self._ckpt_path:
            self._try_resume(step_engine, state)

        # preemption-aware save: flag-based — the handler must not touch jax
        # from signal context, so the loop checkpoints at the next iteration
        old_handlers = []
        self._preempted = False
        if self._preempt_signals:
            import signal as _signal

            if not self._ckpt_path:
                raise ValueError(
                    "set_preemption_checkpoint requires set_checkpoint")

            def _on_preempt(signum, frame):
                self._preempted = True

            for s in self._preempt_signals:
                old_handlers.append((s, _signal.signal(s, _on_preempt)))

        try:
            return self._optimize_loop(step_engine, state)
        finally:
            if self._recompile is not None:
                # a later run's warmup compiles must not be flagged
                self._recompile.mark_warmup()
            if self._profiler is not None:
                self._profiler.close()
            if old_handlers:
                import signal as _signal

                for s, h in old_handlers:
                    _signal.signal(s, h)

    def _arm_perf_accounting(self, engine, step_engine, init_vars,
                             init_args) -> None:
        """Resolve the run's performance-attribution state: the analytic
        FLOPs/step (live MFU numerator), the device peak (denominator),
        the collective-bytes ledger, the attribution accumulator, and the
        recompilation sentinel.  Best-effort — a cost-model failure
        degrades observability, never training."""
        self.attribution = obs_attr.StepAttribution(self.metrics)
        self._attr_t0 = None
        self._attr_dispatch = 0.0
        self._attr_overhead = 0.0
        self._recompile = obs_attr.recompile_sentinel()
        self._recompile.mark_warmup()
        self._flops_per_step = None
        self._eff_flops_per_step = None
        # kept for _refresh_cost_model: a block-sparse mask restore at
        # resume changes effective FLOPs after this first pass ran
        self._cost_model_args = (init_vars, init_args)
        try:
            # shape-capturing walk under eval_shape: no compute, no
            # compile; FLOPs scale linearly from the batch-1 sample to the
            # global batch (the _per_host_batch contract: batch_size IS
            # the global batch)
            detail = obs_cost.train_step_flops_detail(
                self.model, init_vars, init_args, self.batch_size)
            self._flops_per_step = detail["dense"]
            self._eff_flops_per_step = detail["effective"]
            self.metrics.gauge("train.flops_per_step", self._flops_per_step)
            self.metrics.gauge("train.effective_flops_per_step",
                               self._eff_flops_per_step)
        except Exception as e:  # pragma: no cover — exotic custom modules
            log.debug("analytic cost model unavailable (%s); no live MFU "
                      "gauge this run", e)
        self._peak_flops = obs_cost.peak_flops(
            jax.devices()[0].device_kind,
            getattr(engine.config, "peak_flops", None))
        led = obs_cost.collective_ledger(step_engine)
        self._ici_bytes_step = led["ici_bytes_per_step"]
        self._dcn_bytes_step = led["dcn_bytes_per_step"]
        self.metrics.gauge("train.collective_ici_bytes_per_step",
                           self._ici_bytes_step)
        self.metrics.gauge("train.collective_dcn_bytes_per_step",
                           self._dcn_bytes_step)
        # compression view: the gradient scatter (wire dtype + scales,
        # the compressible half) vs the f32 param gather, and the bucket
        # count the overlap scheduler works with
        self.metrics.gauge("train.collective_grad_ici_bytes_per_step",
                           led["grad_ici_bytes_per_step"])
        self.metrics.gauge("train.collective_param_ici_bytes_per_step",
                           led["param_ici_bytes_per_step"])
        self.metrics.gauge("train.grad_comm_buckets", led["comm_buckets"])

    def _optimize_loop(self, step_engine, state) -> TrainedModel:
        engine = Engine.get()
        retries = 0
        retries_by_cause: Dict[Any, int] = {}
        max_retries = engine.config.failure_retry_times
        t_loop = time.perf_counter()
        self._attr_t0 = t_loop
        self._attr_prev_it = state["iteration"]
        while not self.end_when(state):
            if self._preempted:
                # signal landed during epoch-boundary work (validation,
                # triggers) — still honour the save-before-stop contract
                if self.cluster is not None:
                    self.cluster.notify_preemption()
                self._save_checkpoint_once(step_engine, state)
                break
            state["epoch_finished"] = False
            epoch = state["epoch"]
            # exactly-once mid-epoch resume: a checkpoint records how many
            # batches of the current epoch were TRAINED (epoch_batch); the
            # resumed epoch fast-forwards past them instead of replaying
            # the epoch from batch 0.  The skip re-gathers (and discards)
            # at most one epoch of input once per resume — bounded, and
            # the batch plan is deterministic per (seed, epoch).
            # An ELASTIC resume (process_count changed) arrives as a
            # _resume_reshard marker instead: the epoch's remaining
            # examples are re-sharded over the NEW process set
            # (docs/distributed_training.md) — epoch_batch keeps counting
            # GLOBAL steps, which are invariant across process counts.
            skip = int(state.pop("_resume_skip", 0) or 0)
            reshard = state.pop("_resume_reshard", None)
            state["epoch_batch"] = (int(reshard["trained"])
                                    + int(reshard.get("skip", 0) or 0)) \
                if reshard else skip
            batch_iter = self._epoch_batch_iter(step_engine, epoch, skip,
                                                reshard=reshard)
            # observability: time each fetch out of the prefetch pipeline —
            # waiting HERE means the run is input-bound, not device-bound
            batch_iter = self._traced_data(batch_iter)
            # fused multi-step execution: the pipeline lends up to
            # steps_per_call device batches per pull; the span callback
            # clamps each bundle to the per-epoch grid and to trigger
            # edges, and the epoch tail arrives as a remainder bundle
            bundles = pipeline_mod.bundle_batches(
                batch_iter, lambda: self._bundle_span(state))
            try:
                ran_any = False
                for mbs in bundles:
                    ran_any = True
                    prev_it = state["iteration"]
                    self._one_bundle(step_engine, state, mbs)
                    if self._should_log(prev_it, state["iteration"]):
                        self._log_progress(state, t_loop)
                    t_trig = time.perf_counter()
                    self._fire_triggers(step_engine, state)
                    trig_dt = time.perf_counter() - t_trig
                    # attribution: trigger work is the "overhead" component
                    self._attr_overhead += trig_dt
                    # trigger work (validation/checkpoint/histograms) is not
                    # step time: shift the log window start past it
                    if getattr(self, "_last_log", None) is not None:
                        self._last_log = (self._last_log[0] + trig_dt,
                                          self._last_log[1])
                    if self.cluster is not None \
                            and self.cluster.preempt_pending \
                            and not self._preempted:
                        # a PEER host was preempted: the notice propagates
                        # as our own preemption so the whole gang takes
                        # the just-in-time checkpoint, not just the
                        # signalled host
                        log.warning(
                            "cluster preemption notice received: treating "
                            "as local preemption")
                        self._preempted = True
                    if self._preempted:
                        log.warning(
                            "preemption signal received: checkpointing at "
                            "iteration %d and stopping", state["iteration"])
                        if self.cluster is not None:
                            # local SIGTERM → cluster-wide notice (the
                            # handler itself must not touch storage from
                            # signal context; this bundle edge may)
                            self.cluster.notify_preemption()
                        self._save_checkpoint_once(step_engine, state)
                        break
                    if self.end_when(state):
                        break
                else:
                    # epoch boundary: fire epoch triggers while `epoch` still
                    # names the epoch that just finished, then advance.
                    # A resume whose skip consumed the WHOLE epoch (the
                    # checkpoint landed on its last batch) advances without
                    # re-firing — those boundary triggers already ran
                    # before the crash, and a duplicate validation event
                    # would double-feed plateau schedules.
                    if ran_any or skip == 0 or reshard is not None:
                        state["epoch_finished"] = True
                        self._fire_triggers(step_engine, state)
                    state["epoch"] += 1
                    # a resharded epoch's plan dies with the epoch: later
                    # epochs use the normal (seed, epoch, process_count)
                    # plan, and later checkpoints must not carry the marker
                    state.pop("reshard_origin", None)
            except Exception as e:  # driver retry loop (§6.3)
                # A failed train_step may have consumed donated buffers, so
                # recovery REQUIRES a checkpoint to restore from; the epoch
                # restarts cleanly from the resumed driver state.
                # latest_checkpoint accepts only SHARD-COMPLETE dirs, so a
                # manifest orphaned by a crashed sharded write is never the
                # resume point.
                retries += 1
                t_fail = time.perf_counter()
                # dispatched-but-unfetched bundle results are part of the
                # rolled-back step chain; drop them so the next log window
                # never feeds pre-failure losses to the watchdog
                self._pending_losses = []
                self._inflight = 0
                self._last_dispatch_end = None
                cause = classify(e)
                policy = self.failure_policy \
                    or engine.config.resolved_failure_policy()
                cause_policy = policy.policy_for(cause)
                n_cause = retries_by_cause[cause] = \
                    retries_by_cause.get(cause, 0) + 1
                # in-flight async write may BE the latest checkpoint
                self._ckpt_drain(raise_error=False)
                can_resume = (self._ckpt_path and
                              ckpt.latest_checkpoint(self._ckpt_path))
                # bounded BOTH globally and per cause: a poisoned batch
                # replays the identical plan, so its policy allows far
                # fewer in-run retries than a storage blip — exhausting
                # either bound escapes to the Supervisor (or the caller)
                if retries > max_retries or not can_resume \
                        or n_cause > cause_policy.max_retries:
                    raise
                delay = cause_policy.backoff(n_cause)
                log.warning(
                    "iteration failed (%s: %s); retry %d/%d from checkpoint "
                    "[cause %s] in %.2fs", type(e).__name__, e, retries,
                    max_retries, cause.value, delay)
                flight.record("train_in_run_retry", cause=cause.value,
                              retry=retries, iteration=state["iteration"],
                              error=f"{type(e).__name__}: {e}")
                time.sleep(delay)
                if self.cluster is not None:
                    # coordinated rewind: this process is about to restore
                    # an earlier step, so the GANG must restore with it —
                    # post the abort (peers exit their collectives at the
                    # next bundle edge), rendezvous on the next view, and
                    # only then resume together
                    self.cluster.gang_recover(cause.value)
                with trace.span("resilience/in_run_resume",
                                cause=cause.value, retry=retries):
                    self._try_resume(step_engine, state)
                self.metrics.inc("recoveries_total")
                self.metrics.inc(f"retries_by_cause.{cause.value}")
                self.metrics.inc("time_lost_to_recovery_s",
                                 time.perf_counter() - t_fail)
                if self.cluster is not None:
                    # MTTR: failure catch → restored-and-ready wall time
                    self.cluster.note_recovered(
                        time.perf_counter() - t_fail)
                self._last_log = None  # don't count recovery in step time
                # recovery is not attributable step time either: restart
                # the attribution window at the resumed iteration, and
                # clear the per-window timers (data_time et al.) with it —
                # pre-failure data waits in a post-recovery window would
                # over-attribute input time against the restarted wall
                self.metrics.reset()
                self._attr_t0 = time.perf_counter()
                self._attr_prev_it = state["iteration"]
                self._attr_dispatch = 0.0
                self._attr_overhead = 0.0

        if self._recompile is not None:
            # the step loop is over: run-tail work (final checkpoint,
            # get_variables' unravel ops) compiles legitimately
            self._recompile.mark_warmup()
        try:
            self._ckpt_drain()
        except Exception as e:
            # training finished and device state is valid — a failed FINAL
            # write must not discard the model; retry once synchronously
            log.warning("final checkpoint write failed (%s); retrying "
                        "synchronously", e)
            try:
                self._save_checkpoint_sync_last(step_engine, state)
            except Exception as e2:
                log.error("synchronous checkpoint retry also failed: %s", e2)
        variables = step_engine.get_variables()
        self._final_state = dict(state)  # observability: final step/epoch
        if self.attribution is not None and self.attribution.steps:
            # the end-of-run "where did the time go" table; also available
            # programmatically via Optimizer.attribution.report()
            log.info("%s", self.attribution.table())
        return TrainedModel(self.model, variables, step_engine)

    @property
    def final_state(self) -> Optional[Dict[str, Any]]:
        """Driver state at the end of the last completed ``optimize()`` —
        lets callers (tests, the Supervisor) verify e.g. that a faulted
        run reached the same final iteration as a fault-free one."""
        return self._final_state

    # ------------------------------------------------------------------
    def _epoch_batch_iter(self, step_engine, epoch, skip, reshard=None):
        """One epoch's device-ready batch iterator — the streaming input
        pipeline (docs/data.md) when the dataset supports it, the classic
        thread-prefetch path otherwise, both behind the device-dispatch
        lookahead.  ``host_prefetch=0`` forces fully inline production.

        ``reshard`` (an elastic mid-epoch resume marker from
        ``_try_resume``: ``{"process_count": old, "trained": k, "skip":
        extra}``) switches THIS epoch to the re-sharded remainder plan —
        the examples the old process set already trained are excluded and
        the rest re-stride over the new process set
        (``DataSet.resharded_batches``); later epochs revert to the
        normal plan."""
        from bigdl_tpu.data.pipeline import dispatch_to_device

        engine = Engine.get()
        kw = dict(shuffle=True, seed=self.seed, epoch=epoch,
                  process_id=jax.process_index(),
                  process_count=jax.process_count())

        def _skip_closing(inner, n):
            # a bare islice has no close(): abandoning a RESUMED epoch
            # (preemption, end_when, driver retry) must still shut the
            # underlying pipeline's stage threads down, so wrap in a
            # generator whose close propagates
            import itertools

            try:
                yield from itertools.islice(inner, n, None)
            finally:
                close = getattr(inner, "close", None)
                if close is not None:
                    close()

        def _dispatch(batch_iter):
            # dispatch lookahead: host→device DMA double-buffers behind
            # the running step (up to 2 transfers in flight); ring slots
            # release only after their own transfer lands
            return dispatch_to_device(
                batch_iter,
                lambda mb: (step_engine.shard_batch(mb["input"]),
                            step_engine.shard_batch(
                                np.asarray(mb["target"]))),
                size=self.prefetch, metrics=self.metrics)

        if reshard is not None:
            rkw = dict(trained_batches=int(reshard["trained"]),
                       old_process_count=int(reshard["process_count"]),
                       **kw)
            stream = (self.streaming and self.host_prefetch > 0
                      and hasattr(self.dataset,
                                  "resharded_stream_batches"))
            if stream:
                # the remainder epoch keeps the stage-parallel sharded
                # feed: each host streams only its slice of the
                # remaining examples (docs/data.md §Multi-host ingest)
                batch_iter = self.dataset.resharded_stream_batches(
                    self.batch_size,
                    workers=getattr(engine.config, "data_workers", None),
                    metrics=self.metrics, **rkw)
            else:
                batch_iter = self.dataset.resharded_batches(
                    self.batch_size, **rkw)
            skip = int(reshard.get("skip", 0) or 0)
            if skip:
                batch_iter = _skip_closing(batch_iter, skip)
            if self.host_prefetch and not stream:
                batch_iter = thread_prefetch(batch_iter,
                                             depth=self.host_prefetch)
            return _dispatch(batch_iter)
        stream = (self.streaming and self.host_prefetch > 0
                  and hasattr(self.dataset, "stream_batches"))
        if stream:
            # stage-parallel read→decode→assemble into the buffer ring;
            # the pipeline's own threads ARE the host lookahead
            batch_iter = self.dataset.stream_batches(
                self.batch_size,
                workers=getattr(engine.config, "data_workers", None),
                metrics=self.metrics, **kw)
        else:
            batch_iter = self.dataset.batches(self.batch_size, **kw)
        if skip:
            batch_iter = _skip_closing(batch_iter, skip)
        if self.host_prefetch and not stream:
            # host-side lookahead: IO/augmentation runs a thread ahead.
            # (Never stacked on the streaming path: buffering RingBatches
            # in a queue would let their slots be recycled under the
            # consumer; the ring provides the lookahead there.)
            batch_iter = thread_prefetch(batch_iter,
                                         depth=self.host_prefetch)
        return _dispatch(batch_iter)

    def _traced_data(self, batch_iter):
        """The data phase under a span + timer: each ``next()`` on the
        prefetch pipeline is host time the device spends idle.  Waits land
        in the ``train.data_wait_s`` histogram — the /metrics signal that a
        run is input-bound rather than device-bound."""
        it = iter(batch_iter)
        while True:
            with trace.span("train/data"), Timer(self.metrics, "data_time"):
                t0 = time.perf_counter()
                try:
                    mb = next(it)
                except StopIteration:
                    return
                self.metrics.observe("train.data_wait_s",
                                     time.perf_counter() - t0)
            yield mb

    def _bundle_span(self, state) -> int:
        """How many steps the NEXT bundle may span.  Bundle edges live on
        the per-epoch grid (epoch_batch multiples of K) so a mid-epoch
        resume re-aligns to the boundaries an uninterrupted run used, and
        iteration-structured triggers (``Trigger.boundary`` hints) shorten
        a bundle so their firing step lands exactly on a bundle edge —
        ``several_iteration(4)`` still checkpoints at iteration 4 under
        ``steps_per_call=8``.  Triggers without iteration structure
        (loss/score/plateau) quantize to bundle granularity."""
        k = self._bundle_k
        if k <= 1:
            return 1
        if self._preempted or (self.cluster is not None
                               and self.cluster.preempt_pending):
            # a preemption is pending: the signal can only be honoured at
            # a bundle edge, so the NEXT bundle shrinks to one step and
            # the just-in-time checkpoint lands ~1 step after the signal
            # instead of up to K steps later
            return 1
        span = k - state.get("epoch_batch", 0) % k
        it = state["iteration"]
        for t in (self.end_when, self._val_trigger, self._ckpt_trigger,
                  self._summary_triggers.get("Parameters")):
            b = getattr(t, "boundary", None) if t is not None else None
            if b is None:
                continue
            edge = b(it)
            if edge is not None and 0 < edge < span:
                span = edge
        return span

    def _one_bundle(self, step_engine, state, mbs):
        """Dispatch ``len(mbs)`` consecutive steps as ONE XLA program.
        Fault injection fires host-side for every step in the range (the
        host only regains control at bundle edges); per-step losses come
        back as a device vector fetched lazily at the next log point."""
        it0 = state["iteration"]
        k = len(mbs)
        now = time.perf_counter()
        if self._last_dispatch_end is not None:
            # host time since the previous dispatch returned — the
            # per-step overhead bundling amortizes (÷ bundle size)
            self.metrics.observe("train.dispatch_gap_s",
                                 now - self._last_dispatch_end)
        with trace.span("train/bundle", step=it0, size=k):
            if self.cluster is not None:
                # cluster hazards first (peer abort flags, propagated
                # preemption notices, injected host loss) — a gang-level
                # condition must win over a local per-step fault
                self.cluster.on_step(it0, k)
            faults.fire_bundle(it0, k)  # slow_host / process_kill /
            #                             step_fail per step in the range
            if self.watchdog is not None:
                self.watchdog.step_started(it0)
            for j in range(k):
                with trace.span("train/step", step=it0 + j):
                    if self._profiler is not None:
                        self._profiler.step(it0 + j)
            xs = [mb[0] for mb in mbs]
            ys = [mb[1] for mb in mbs]
            with trace.span("train/dispatch", step=it0, size=k):
                t0 = time.perf_counter()
                losses, gnorms = step_engine.train_bundle_device(
                    it0, xs, ys)
                disp_dt = time.perf_counter() - t0
                # per-step normalized so the mean stays comparable
                # across bundle sizes (the auto-K pick reads it)
                self.metrics.add("step_dispatch", disp_dt / k)
                self._attr_dispatch += disp_dt
        self._last_dispatch_end = time.perf_counter()
        if self._recompile is not None:
            self._recompile.note_step(it0 + k)
        # collective-bytes ledger: every dispatched step moves the same
        # sync traffic (the layout is static for the run)
        if self._ici_bytes_step:
            self.metrics.inc("train.collective_ici_bytes_total",
                             self._ici_bytes_step * k)
        if self._dcn_bytes_step:
            self.metrics.inc("train.collective_dcn_bytes_total",
                             self._dcn_bytes_step * k)
        self._pending_losses.append((it0, losses, gnorms))
        self._inflight += k
        self.metrics.gauge("train.steps_in_flight", self._inflight)
        self.metrics.gauge("train.bundle_size", k)
        state["loss"] = losses[-1]  # device scalar; float() when read
        state["iteration"] = it0 + k
        state["epoch_batch"] = state.get("epoch_batch", 0) + k

    def _should_log(self, prev_it: int, it: int) -> bool:
        # a log point is any multiple of log_every inside (prev_it, it] —
        # bundles quantize the cadence up to their edges
        return it // self.log_every > prev_it // self.log_every

    def _log_progress(self, state, t_loop):
        it = state["iteration"]
        # fetching the loss VALUES blocks until the step chain has actually
        # executed (they are data-dependent on every dispatched bundle), so
        # the wall-clock window between log points measures real step
        # time — not async dispatch time, which flatters when the in-flight
        # queue hides device latency.
        with trace.span("train/device_sync", step=it):
            pending, self._pending_losses = self._pending_losses, []
            fetched = jax.device_get([(lv, gv) for _, lv, gv in pending])
            loss = float(state["loss"])
        state["loss"] = loss
        self._inflight = 0
        self.metrics.gauge("train.steps_in_flight", 0)
        # per-step granularity survives bundling: every bundle returned a
        # length-K loss/grad-norm vector — record the full curves first,
        # then feed the NaN watchdog (which may raise PoisonedStepError
        # into the retry loop after nan_patience bad observations; the
        # fetch above already forced the sync, so none of this costs an
        # extra transfer)
        for (it0, _, _), (lv, gv) in zip(pending, fetched):
            lv, gv = np.ravel(lv), np.ravel(gv)
            for j in range(len(lv)):
                self.metrics.observe("train.grad_norm", float(gv[j]))
                if self._train_summary:
                    self._train_summary.add_scalar(
                        "loss", float(lv[j]), it0 + j + 1)
        if self.watchdog is not None:
            for (it0, _, _), (lv, _) in zip(pending, fetched):
                lv = np.ravel(lv)
                for j in range(len(lv)):
                    self.watchdog.observe_loss(it0 + j, float(lv[j]))
        now = time.perf_counter()
        last = getattr(self, "_last_log", None)
        dt_is_wall = last is not None and it > last[1]
        if dt_is_wall:
            dt = (now - last[0]) / (it - last[1])
        else:  # first window: includes compile; dispatch mean is the best proxy
            dt = self.metrics.mean("step_dispatch")
        self._last_log = (now, it)
        # step wall time into the run-lifetime histogram: exact per-step
        # at log_every=1 (the default); a coarser log cadence records the
        # WINDOW MEAN once per window, which smooths tails — measuring a
        # true per-step time would require blocking every dispatch
        if dt > 0:
            self.metrics.observe("train.step_time_s", dt)
        if self._bundle_auto and not self._bundle_picked \
                and dt_is_wall and dt > 0:
            self._pick_bundle_size(dt)
        self._account_window(it, now, dt, dt_is_wall)
        self.metrics.reset()  # rolling window: throughput reflects recent steps
        lr = float(np.asarray(self.optim_method.get_learning_rate(it - 1)))
        throughput = self.batch_size / max(dt, 1e-9)
        log.info(
            "Epoch %d Iteration %d: loss %.4f, lr %.5g, ~%.0f records/s",
            state["epoch"], it, loss, lr, throughput)
        if self._train_summary:
            self._train_summary.add_scalar("lr", lr, it)
            self._train_summary.add_scalar("throughput", throughput, it)

    def _account_window(self, it: int, now: float, dt: float,
                        dt_is_wall: bool) -> None:
        """Close one attribution window at a log point: decompose the
        window's wall time into data/dispatch/device/overhead, export the
        live MFU gauge, and (multi-process) the straggler-skew gauges.
        Reads the per-window timers BEFORE the caller's metrics.reset().
        ``dt_is_wall=False`` marks the dispatch-mean proxy windows (first
        window, first after recovery): a proxy dt is ~1000x the true wall
        off on real hardware, so MFU/straggler gauges skip those — the
        warmup is symmetric across hosts, so the allgather stays matched."""
        steps_w = it - self._attr_prev_it
        t0 = self._attr_t0
        if steps_w > 0 and t0 is not None and self.attribution is not None:
            self.attribution.window(
                steps_w, now - t0,
                data_s=self.metrics.total("data_time"),
                dispatch_s=self._attr_dispatch,
                overhead_s=self._attr_overhead)
        self._attr_t0 = now
        self._attr_prev_it = it
        self._attr_dispatch = 0.0
        self._attr_overhead = 0.0
        if self._recompile is not None and self.attribution is not None \
                and self.attribution.windows >= 2 \
                and not self._recompile.steady:
            # warmup is over after TWO full windows: the first holds the
            # train-program compile, the second flushes the log-point's
            # own eager-op compiles (LR schedule math, summary plumbing).
            # New bundle-size/eval programs announce themselves via
            # expected_compile in the step engine, so from here anything
            # else is a mid-run cache miss
            self._recompile.mark_steady(it)
        if dt_is_wall and dt > 0 and self._flops_per_step:
            achieved = self._flops_per_step / dt / jax.device_count()
            self.metrics.gauge("train.achieved_flops_per_chip", achieved)
            m = obs_cost.mfu(self._flops_per_step, dt, jax.device_count(),
                             self._peak_flops)
            if m is not None:
                self.metrics.gauge("train.mfu", m)
            # effective MFU: nonzero-block work only — under block
            # sparsity train.mfu is the dense-equivalent view and THIS is
            # the honest chip utilization; for dense models they are equal
            if self._eff_flops_per_step:
                em = obs_cost.mfu(self._eff_flops_per_step, dt,
                                  jax.device_count(), self._peak_flops)
                if em is not None:
                    self.metrics.gauge("train.effective_mfu", em)
        if dt_is_wall and dt > 0 and jax.process_count() > 1:
            try:
                stats = obs_attr.host_step_time_stats(dt)
            except Exception as e:  # pragma: no cover — backend quirks
                log.debug("straggler allgather failed: %s", e)
                stats = None
            if stats:
                self.metrics.gauge("train.step_time_max_s", stats["max"])
                self.metrics.gauge("train.step_time_min_s", stats["min"])
                self.metrics.gauge("train.step_time_skew_s", stats["skew"])

    def _pick_bundle_size(self, step_time_s: float) -> None:
        """``steps_per_call="auto"``: after the first full log window
        (compile excluded), compare the measured per-step host dispatch
        time against step wall time and pick K so dispatch amortizes to
        ~2% of wall — small fast steps get deep bundles, big slow steps
        stay at K=1 where bundling only delays triggers."""
        self._bundle_picked = True
        disp = self.metrics.mean("step_dispatch")
        ratio = disp / step_time_s if step_time_s > 0 else 0.0
        k = 1 if ratio < 0.02 else int(min(32, max(2, np.ceil(ratio / 0.02))))
        if k != self._bundle_k:
            log.info(
                "steps_per_call=auto: per-step dispatch %.3f ms vs step "
                "%.3f ms (%.0f%%) -> bundling %d steps per XLA call",
                disp * 1e3, step_time_s * 1e3, 100 * ratio, k)
            flight.record("bundle_auto_pick", k=k, dispatch_s=disp,
                          step_s=step_time_s)
        self._bundle_k = k

    def _fire_triggers(self, step_engine, state):
        # each concern fires at most once per iteration (an iteration-count
        # trigger would otherwise re-fire at the epoch-boundary call)
        it = state["iteration"]
        if (self._val_trigger and self._val_trigger(state)
                and self._last_val_iter != it):
            self._last_val_iter = it
            self._run_validation(step_engine, state)
        if (self._ckpt_trigger and self._ckpt_trigger(state)
                and self._ckpt_path and self._last_ckpt_iter != it):
            self._last_ckpt_iter = it
            self._save_checkpoint(step_engine, state)
        hist_trigger = self._summary_triggers.get("Parameters")
        if (hist_trigger and self._train_summary and hist_trigger(state)
                and self._last_hist_iter != it):
            self._last_hist_iter = it
            variables = step_engine.get_variables()
            # ONE batched device→host fetch of the whole params tree — a
            # per-leaf np.asarray would block on a separate transfer per
            # parameter (hundreds of round-trips on a real model)
            host_params = jax.device_get(variables["params"])
            for path, leaf in jax.tree_util.tree_flatten_with_path(
                    host_params)[0]:
                tag = "Parameters/" + "/".join(
                    str(getattr(k, "key", k)) for k in path)
                self._train_summary.add_histogram(tag, leaf, it)

    def _save_checkpoint_once(self, step_engine, state):
        """Checkpoint unless this iteration was already checkpointed (the
        trigger may have fired just before a preemption break)."""
        if self._ckpt_path is None:
            # a cluster-propagated preemption can reach a run that never
            # called set_checkpoint; stopping cleanly is all it can do
            log.warning("preemption stop without set_checkpoint: no "
                        "just-in-time checkpoint to take")
            return
        if self._last_ckpt_iter != state["iteration"]:
            self._last_ckpt_iter = state["iteration"]
            self._save_checkpoint(step_engine, state)

    def _save_checkpoint(self, step_engine, state):
        state["loss"] = float(state["loss"])
        # Snapshot unconditionally: the async writer serializes driver_state
        # in a background thread while the training loop keeps mutating the
        # live dict, so the manifest could otherwise record a later iteration
        # than the params it accompanies.
        state = dict(state)
        schedule = getattr(self.optim_method, "schedule", None)
        if schedule is not None and hasattr(schedule, "state_dict"):
            state["schedule_state"] = schedule.state_dict()
        kw = self._ckpt_kwargs(step_engine, state,
                               sync_barrier=self._ckpt_async is None)
        if self._ckpt_async is not None:
            self._ckpt_async.submit(self._ckpt_path,
                                    state["iteration"], **kw)
        else:
            ckpt.save_checkpoint(self._ckpt_path, state["iteration"], **kw)
        if self.cluster is not None:
            # peer-shard publish rides the checkpoint trigger: each host
            # pushes its ZeRO-1 shard (leader adds the replicated params)
            # onto the control channel, so a rejoining process can restore
            # from its buddies without touching the checkpoint bucket.
            # Best-effort — a failed publish degrades the recovery ladder
            # (checkpoint rung still holds), never training
            try:
                self.cluster.publish_state(step_engine, state)
            except Exception as e:
                log.warning("peer-shard publish failed: %s", e)

    def _refresh_cost_model(self) -> None:
        """Recompute the live-MFU numerators after a host-side model
        structure change (block-sparse masks restored at resume) — the
        first _arm_perf_accounting pass ran before the masks existed."""
        init_vars, init_args = getattr(self, "_cost_model_args",
                                       (None, None))
        if init_vars is None:
            return
        try:
            detail = obs_cost.train_step_flops_detail(
                self.model, init_vars, init_args, self.batch_size)
            self._flops_per_step = detail["dense"]
            self._eff_flops_per_step = detail["effective"]
            self.metrics.gauge("train.flops_per_step", self._flops_per_step)
            self.metrics.gauge("train.effective_flops_per_step",
                               self._eff_flops_per_step)
        except Exception as e:  # pragma: no cover — cost model optional
            log.debug("cost-model refresh failed (%s)", e)

    def _ckpt_kwargs(self, step_engine, state, sync_barrier: bool):
        """The save_checkpoint argument set: gathered single-writer by
        default, per-process opt-state shards when sharded checkpointing
        is active.  Shards are fetched to host EAGERLY (the async writer
        must never touch live device state), and the cross-process
        barrier is only used on the synchronous path — a barrier inside
        the async writer thread could interleave with the training
        step's own collectives and deadlock; the READER instead verifies
        every shard file exists before trusting a sharded manifest."""
        # the mid-epoch batch plan is keyed by process_count; recording it
        # in every written driver_state lets an elastic resume detect the
        # key changed (see _try_resume) — `state` is already a snapshot on
        # both call paths, so mutating it here is safe
        state["process_count"] = jax.process_count()
        # block-sparse FFN masks are host MODULE state, not params — ride
        # the driver_state so a restarted process resumes the same
        # sparsity pattern instead of silently training dense again
        from bigdl_tpu.ops.block_sparse import collect_masks

        sparse_masks = collect_masks(self.model)
        if sparse_masks:
            state["block_sparse_masks"] = sparse_masks
        kw = dict(model_state=host_fetch(step_engine.model_state),
                  driver_state=state)
        if self._ckpt_mirror:
            kw["mirror"] = self._ckpt_mirror
        sharded = self._ckpt_use_shards(step_engine)
        # params/EMA are replicated: in sharded mode only process 0's copy
        # is ever written, so the other (n-1) hosts skip the full-model
        # device→host materialization entirely
        if not sharded or jax.process_index() == 0:
            kw["flat_params"] = np.asarray(step_engine.flat_params)
            if step_engine.ema_flat is not None:
                kw["ema_flat"] = np.asarray(step_engine.ema_flat)
        if sharded:
            kw["opt_shards"] = ckpt.local_opt_shards(step_engine.opt_state)
            kw["shard_index"] = jax.process_index()
            kw["shard_count"] = jax.process_count()
            kw["attempt"] = self._ckpt_attempt_token(state["iteration"])
            if sync_barrier and jax.process_count() > 1:
                from jax.experimental import multihost_utils

                it = state["iteration"]
                kw["barrier"] = lambda: multihost_utils.sync_global_devices(
                    f"bigdl-tpu-ckpt-{it}")
        else:
            kw["opt_state"] = host_fetch(step_engine.opt_state)
        return kw

    @staticmethod
    def _ckpt_attempt_token(iteration: int) -> str:
        """One uuid per SAVE, agreed by every process: generated on
        process 0 and broadcast on the MAIN thread (a collective here is
        deterministic program order; inside the async writer thread it
        could interleave with the training step's collectives and
        deadlock).  The token makes shard files attempt-unique so a
        manifest can never certify a stale shard from a crashed earlier
        attempt at the same step."""
        import uuid

        if jax.process_count() == 1:
            return uuid.uuid4().hex[:8]
        from jax.experimental import multihost_utils

        tok = np.frombuffer(
            uuid.uuid4().hex[:8].encode(), np.uint8).copy() \
            if jax.process_index() == 0 else np.zeros(8, np.uint8)
        tok = multihost_utils.broadcast_one_to_all(tok)
        return bytes(np.asarray(tok)).decode()

    def _ckpt_use_shards(self, step_engine) -> bool:
        if not step_engine.optim.elementwise:
            return False  # replicated opt state: nothing to shard
        if self._ckpt_sharded == "auto":
            return jax.process_count() > 1
        return bool(self._ckpt_sharded)

    def _save_checkpoint_sync_last(self, step_engine, state):
        ckpt.save_checkpoint(
            self._ckpt_path, state["iteration"],
            **self._ckpt_kwargs(
                step_engine, dict(state, loss=float(state["loss"])),
                sync_barrier=True))

    def _ckpt_drain(self, raise_error: bool = True):
        """Join any in-flight async write (resume and exit paths read
        latest_checkpoint, which must see a completed directory)."""
        if self._ckpt_async is not None:
            self._ckpt_async.wait(raise_error=raise_error)

    def _run_validation(self, step_engine, state):
        batches = self._val_dataset.batches(
            self._val_batch, shuffle=False, drop_last=False,
            process_id=jax.process_index(), process_count=jax.process_count())
        results = step_engine.evaluate(self._val_methods, batches)
        for r in results:
            log.info("validation [%s] epoch %d iter %d: %s",
                     r.name, state["epoch"], state["iteration"], r.result)
            if self._val_summary:
                self._val_summary.add_scalar(r.name, r.result,
                                             state["iteration"])
        if results:
            state["score"] = results[0].result
            # observation counter for event-cadenced triggers
            # (Trigger.plateau counts validation events, not iterations)
            state["n_validations"] = state.get("n_validations", 0) + 1
            # reduce-on-plateau feedback (reference SGD.Plateau): the
            # schedule decides host-side; an LR change needs a recompile
            schedule = getattr(self.optim_method, "schedule", None)
            if schedule is not None and hasattr(schedule, "on_score"):
                monitor = getattr(schedule, "monitor", None)
                picked = results[0]
                if monitor is not None:
                    matches = [r for r in results if r.name == monitor]
                    if not matches:
                        raise ValueError(
                            f"Plateau monitor {monitor!r} not among "
                            f"validation methods {[r.name for r in results]}")
                    picked = matches[0]
                if schedule.on_score(float(picked.result)):
                    log.info("Plateau: reducing LR (factor now %g); "
                             "recompiling train step",
                             schedule.current_factor)
                    step_engine._train = step_engine._build_train()

    def _try_resume(self, step_engine, state):
        """Restore device + driver state from the best available source —
        the recovery LADDER (docs/resilience.md §Multi-host recovery):

        1. **peer-shard store** (cluster attached, complete step at least
           as new as the newest checkpoint): replicated params + the
           ZeRO-1 optimizer shards the peers published on the control
           channel — bit-identical to a checkpoint restore of the same
           step, without touching the checkpoint bucket;
        2. **newest shard-complete checkpoint**;
        3. elastic tail: a ``process_count`` change mid-epoch re-shards
           the epoch's remaining examples over the new process set
           (``DataSet.resharded_batches``), falling back to
           replay-from-epoch-start only when the dataset cannot reshard
           or the process set changed twice in one epoch."""
        from bigdl_tpu.utils import storage as _storage

        latest = ckpt.latest_checkpoint(self._ckpt_path) \
            if self._ckpt_path else None
        ckpt_step = None
        if latest is not None:
            try:
                ckpt_step = int(_storage.basename(latest).split("-")[1])
            except (ValueError, IndexError):
                ckpt_step = None
        loaded = path_used = None
        if self.cluster is not None:
            peer_step = self.cluster.store.latest_complete_step()
            if peer_step is not None and (ckpt_step is None
                                          or peer_step >= ckpt_step):
                try:
                    loaded = self.cluster.load_peer_state(
                        peer_step, step_engine.opt_template,
                        step_engine.model_state_template)
                    path_used = "peer_shard"
                except Exception as e:
                    log.warning(
                        "peer-shard restore of step %d failed (%s: %s); "
                        "falling back to the checkpoint rung", peer_step,
                        type(e).__name__, e)
        if loaded is None:
            if latest is None:
                return
            loaded = ckpt.load_checkpoint(
                latest,
                opt_state_template=step_engine.opt_template,
                model_state_template=step_engine.model_state_template)
            path_used = "checkpoint"
        flat, opt_state, model_state, driver, ema = loaded
        if self.cluster is not None:
            n_bytes = int(
                np.asarray(flat).nbytes
                + sum(np.asarray(a).nbytes for a in
                      jax.tree_util.tree_leaves(opt_state))
                + sum(np.asarray(a).nbytes for a in
                      jax.tree_util.tree_leaves(model_state)))
            self.metrics.inc(f"cluster.recovery_by_path.{path_used}")
            self.metrics.inc("cluster.recovery_bytes_total", n_bytes)
            flight.record("cluster_restore", path=path_used,
                          step=int(driver.get("iteration", 0) or 0),
                          bytes=n_bytes)
        step_engine.flat_params = put_sharded(
            jax.numpy.asarray(flat), step_engine._rep)
        if step_engine.ema_flat is not None:
            # a failed donated step consumed the old EMA buffer too; restore
            # the checkpointed EMA, or re-seed from the restored params when
            # the checkpoint predates EMA
            src = ema if ema is not None else flat
            step_engine.ema_flat = put_sharded(
                jax.numpy.asarray(src).copy(), step_engine._rep)
        opt_sh = (step_engine._sharded_vec if step_engine.optim.elementwise
                  else step_engine._rep)
        step_engine.opt_state = put_sharded(opt_state, opt_sh)
        step_engine.model_state = put_sharded(model_state, step_engine._rep)
        state.update(driver)
        saved_masks = state.pop("block_sparse_masks", None)
        if saved_masks:
            # restore the checkpoint's sparsity pattern; if it differs
            # from the live modules' masks (fresh process: all-ones), the
            # engine's compiled programs traced the WRONG pattern — the
            # mask is a trace-time constant jit cannot see — so drop them
            # and retrace on the next step
            from bigdl_tpu.ops.block_sparse import (apply_masks,
                                                    collect_masks)

            before = collect_masks(self.model)
            n = apply_masks(self.model, saved_masks)
            if n and collect_masks(self.model) != before:
                step_engine.rebuild_programs()
                self._refresh_cost_model()
                log.info("restored block-sparse masks for %d modules; "
                         "programs retrace", n)
                flight.record("block_sparse_masks_restored", modules=n)
        state["epoch_finished"] = False
        # rolled back: trigger bookkeeping beyond the resumed iteration is
        # stale future state — without this reset, a checkpoint/validation
        # trigger that FAILED at iteration N would never re-fire when the
        # replay reaches N again (the run would end missing its last
        # checkpoint).  The resumed iteration itself stays marked: the
        # checkpoint being resumed from IS that iteration's firing.
        it = int(driver.get("iteration", 0) or 0)
        self._last_ckpt_iter = min(self._last_ckpt_iter, it)
        self._last_val_iter = min(self._last_val_iter, it)
        self._last_hist_iter = min(self._last_hist_iter, it)
        # fast-forward the resumed epoch past the batches already trained —
        # from the CHECKPOINT's counter, never the live state's: on the
        # in-run retry path the live epoch_batch reflects rolled-back
        # training (a pre-epoch_batch-era checkpoint must replay, not skip)
        state["epoch_batch"] = int(driver.get("epoch_batch", 0) or 0)
        state["_resume_skip"] = state["epoch_batch"]
        # ELASTIC resume: sharded checkpoints load at any process count,
        # but the per-process batch plan is keyed by (seed, epoch,
        # process_id, process_count) — a skip computed under N processes
        # does not line up with what was trained when resuming under M.
        # When the dataset supports it, the epoch's REMAINING examples are
        # re-sharded deterministically over the new process set (the old
        # plan's trained prefix is reconstructible from (seed, epoch), so
        # shrink/grow loses nothing beyond the post-checkpoint steps);
        # replay-from-epoch-start survives only as the fallback for
        # datasets that cannot reshard or a twice-changed process set.
        saved_pc = driver.get("process_count")
        state["process_count"] = jax.process_count()
        origin = driver.get("reshard_origin")
        pc_changed = (saved_pc is not None
                      and int(saved_pc) != jax.process_count())
        can_reshard = hasattr(self.dataset, "resharded_batches")

        def _replay_epoch(why: str) -> None:
            log.warning(
                "elastic resume: checkpoint written at process_count=%s, "
                "resuming at %d — %s, so epoch %d REPLAYS from its start "
                "(%d mid-epoch batches re-trained rather than silently "
                "dropped)", saved_pc, jax.process_count(), why,
                state["epoch"], state["_resume_skip"])
            state["epoch_batch"] = 0
            state["_resume_skip"] = 0
            state.pop("reshard_origin", None)
            self.metrics.inc("elastic_resumes_total")

        if origin is not None and state["_resume_skip"]:
            # resuming INTO an epoch that already runs on a re-sharded
            # plan: rebuild the same remainder plan and skip the batches
            # of it trained since the reshard point
            if pc_changed or not can_reshard:
                _replay_epoch("the process set changed again mid-epoch")
            else:
                base = int(origin["trained"])
                state["_resume_reshard"] = {
                    "process_count": int(origin["process_count"]),
                    "trained": base,
                    "skip": max(0, state["epoch_batch"] - base)}
                state["_resume_skip"] = 0
        elif pc_changed and state["_resume_skip"]:
            if can_reshard:
                log.warning(
                    "elastic resume: checkpoint written at "
                    "process_count=%d, resuming at %d — epoch %d continues "
                    "on a re-sharded batch plan (the %d already-trained "
                    "global batches are excluded; nothing replays, nothing "
                    "is dropped)", int(saved_pc), jax.process_count(),
                    state["epoch"], state["epoch_batch"])
                state["_resume_reshard"] = {
                    "process_count": int(saved_pc),
                    "trained": state["epoch_batch"], "skip": 0}
                state["reshard_origin"] = {
                    "process_count": int(saved_pc),
                    "trained": state["epoch_batch"]}
                state["_resume_skip"] = 0
                self.metrics.inc("elastic_resumes_total")
                self.metrics.inc("elastic_resharded_total")
                flight.record("elastic_reshard", epoch=state["epoch"],
                              old_pc=int(saved_pc),
                              new_pc=jax.process_count(),
                              trained=state["epoch_batch"])
            else:
                _replay_epoch("the per-process batch plan differs and "
                              "this dataset cannot reshard mid-epoch")
        sched_state = state.pop("schedule_state", None)
        schedule = getattr(self.optim_method, "schedule", None)
        if sched_state is not None and schedule is not None \
                and hasattr(schedule, "load_state_dict"):
            schedule.load_state_dict(sched_state)
            # the restored factor must be baked into the compiled step
            step_engine._train = step_engine._build_train()
        log.info("resumed via %s from %s (iteration %d, epoch %d)",
                 path_used, latest if path_used == "checkpoint"
                 else "peer-shard store",
                 state["iteration"], state["epoch"])


# Reference-parity aliases: the factory in the reference picks the variant by
# dataset type; here the mesh size does, so these are the same class.
DistriOptimizer = Optimizer
LocalOptimizer = Optimizer
