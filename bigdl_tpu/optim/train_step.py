"""The distributed train step — heart of the framework.

Reference analog (unverified — mount empty): ``dllib/optim/DistriOptimizer.
scala`` task body + ``optim/parameters/AllReduceParameter.scala``: weights are
flattened into ONE contiguous 1-D storage, gradients are split into
``partitionNum`` chunks pushed through Spark's BlockManager, each partition
owner sums its slice, applies the OptimMethod **on the slice only** (optimizer
state lives sharded — ZeRO-1, 2016 vintage), publishes the updated slice, and
every task gathers all slices next iteration.

TPU-native mapping (this file): the same algorithm as ONE ``shard_map``-ped
XLA program over the mesh's "data" axis —

    flat grads --psum_scatter--> grad slice       (BlockManager put+sum)
    OptimMethod.update(slice)                     (partition-owner update)
    --all_gather--> new flat params               (next-iteration getWeights)

so the BlockManager/netty transport becomes ICI collectives and the two Spark
stages per iteration become zero host round-trips.  Gradient compression
(``FP16CompressedTensor``) maps to the ``grad_comm`` wire-format knob:
``"bf16"`` halves the gradient bytes, ``"int8"`` blockwise-quantizes them
(EQuARX recipe — int8 payload + per-block scales, summed in a widened f32
accumulator; see ``parallel/collectives.py``), and ``comm_bucket_bytes``
splits the sync into buckets XLA can overlap with neighbouring compute.
See PAPERS.md "Automatic Cross-Replica Sharding of Weight Update in
Data-Parallel Training" for why this is the native XLA form.
"""

import functools
import warnings
from contextlib import nullcontext
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bigdl_tpu.obs.attr import expected_compile
from bigdl_tpu.optim.validation import StatsAccumulator
from bigdl_tpu.parallel import collectives
from bigdl_tpu.runtime.mesh import (AXIS_DATA, AXIS_DCN, AXIS_SEQ,
                                    axis_size, shard_map)


def as_inputs(x):
    """Model-input convention: a tuple is a multi-input pack, anything else
    is the single input."""
    return x if isinstance(x, tuple) else (x,)


@dataclass
class GradientClipping:
    """Reference ``optim/parameters/ParameterProcessor.scala``:
    ConstantClippingProcessor / L2NormClippingProcessor."""

    constant_min: Optional[float] = None
    constant_max: Optional[float] = None
    l2_norm: Optional[float] = None


def host_fetch(tree):
    """Fetch a (possibly multi-host sharded) pytree to host numpy on every
    process.  Single-process: plain device_get.  Multi-process: allgather the
    non-addressable shards first (checkpoint-time only; not on the hot path)."""
    if jax.process_count() == 1:
        return jax.device_get(tree)
    from jax.experimental import multihost_utils

    return jax.device_get(multihost_utils.process_allgather(tree, tiled=True))


def put_sharded(tree, sharding):
    """Inverse of host_fetch: place full host arrays with ``sharding`` in a
    way that works under multi-controller (each process contributes only its
    addressable shards)."""
    if jax.process_count() == 1:
        return jax.device_put(tree, sharding)

    def put_one(x):
        x = np.asarray(x)
        return jax.make_array_from_callback(
            x.shape, sharding, lambda idx: x[idx])

    return jax.tree_util.tree_map(put_one, tree)


def _iter_modules(module, seen=None):
    """Best-effort walk of a module tree (containers, attribute children,
    lists of children)."""
    from bigdl_tpu.nn.module import Module

    if seen is None:
        seen = set()
    if id(module) in seen:
        return
    seen.add(id(module))
    yield module
    for v in vars(module).values():
        children = v if isinstance(v, (list, tuple)) else [v]
        for c in children:
            if isinstance(c, Module):
                yield from _iter_modules(c, seen)


def _check_seq_parallel_model(model) -> None:
    """Sequence-sharded inputs feed PLAIN attention block-diagonal windows
    (silently wrong numerics), so seq_parallel training demands
    seq-parallel-aware attention layers.  Models with no catalog attention
    at all (hand-written kernels) only get a warning."""
    from bigdl_tpu.nn.attention import MultiHeadAttention
    from bigdl_tpu.utils.log import get_logger

    mhas = [m for m in _iter_modules(model)
            if isinstance(m, MultiHeadAttention)]
    if mhas and not any(m.seq_parallel for m in mhas):
        raise ValueError(
            "seq_parallel=True but none of the model's attention layers "
            "is sequence-parallel-aware — build them with "
            "MultiHeadAttention/TransformerLayer(seq_parallel='ring'|"
            "'ulysses') or plain attention will silently attend only "
            "within each sequence block")
    if not mhas:
        get_logger("bigdl_tpu.optim").warning(
            "seq_parallel=True with no catalog attention layers found: "
            "make sure custom attention uses the seq-axis collectives")


class ShardedParameterStep:
    """Builds the jitted ZeRO-1 train/eval steps for a model+criterion over a
    mesh.  Owns the flat-parameter layout (the ``AllReduceParameter`` role)."""

    def __init__(self, model, criterion, optim_method, mesh: Mesh,
                 init_variables: Dict[str, Any],
                 clip: Optional[GradientClipping] = None,
                 bf16_grads: bool = False, remat: bool = False,
                 remat_policy: Optional[str] = None,
                 accum_steps: int = 1, ema_decay: float = 0.0,
                 seq_parallel: bool = False, trainable_mask=None,
                 grad_comm: Optional[str] = None,
                 comm_bucket_bytes: Optional[int] = None,
                 quant_block: int = collectives.DEFAULT_QUANT_BLOCK,
                 param_comm: Optional[str] = None):
        """``grad_comm``: wire format of the gradient sync
        (docs/parallelism.md §Gradient compression) —

        - ``"fp32"`` (default): full-precision reduce-scatter, the
          original cycle.
        - ``"bf16"``: bfloat16 reduce-scatter — halves the gradient's
          collective bytes (the FP16CompressedTensor analog).
        - ``"int8"``: blockwise-quantized reduce-scatter (EQuARX recipe):
          int8 payload + one f32 scale per ``quant_block`` elements over
          an ``all_to_all``, summed in a widened f32 accumulator — ~4x
          fewer gradient bytes on ICI and DCN.  The optimizer update
          always runs on the f32 master params; a single-device data
          axis skips quantization entirely (no wire, no rounding).

        ``param_comm``: wire format of the updated-param all_gather
        (the other half of the ZeRO-1 cycle's ICI bytes) —

        - ``"fp32"`` (default): full-precision gather, the original
          cycle — byte-identical params on every rank by construction.
        - ``"int8"``: gather the blockwise-int8 UPDATE DELTA
          (``new - old`` per shard chunk) + f32 per-block scales and
          reconstruct ``base + dequantized delta`` against the
          replicated flat params — ~4x fewer param-gather ICI bytes.
          The gathered bytes are identical on every rank, so params
          stay bit-identical replicated; the per-step rounding rides
          the small delta, not the param magnitude, and passes the
          same loss-parity gate as ``grad_comm="int8"``
          (tests/test_grad_comm.py).  Master params and the optimizer
          update stay f32.

        ``bf16_grads``: DEPRECATED spelling of ``grad_comm="bf16"``;
        still accepted (with a warning) so existing configs keep working.

        ``comm_bucket_bytes``: split the gradient sync into buckets of at
        most this many flat-gradient bytes, one collective per bucket
        dispatched as its slice of the backward's gradient is consumed —
        bucket *k*'s optimizer update and param gather depend only on
        bucket *k*'s reduce-scatter, the dependence structure XLA's
        latency-hiding scheduler needs to overlap communication with
        neighbouring buckets' compute.  ``None`` keeps one monolithic
        transfer; shard ownership (and therefore optimizer-state layout
        and checkpoints) is identical for every bucket size.

        The optimizer update still runs on the f32 master params.

        ``remat``: wrap the forward in ``jax.checkpoint`` so the backward
        recomputes activations instead of storing them — trades FLOPs for
        HBM on memory-bound models (big batch / long sequence).

        ``accum_steps``: gradient accumulation — each device splits its
        per-step batch into ``accum_steps`` microbatches, runs fwd+bwd per
        microbatch under ``lax.scan`` (activations for ONE microbatch live
        at a time) summing flat gradients in f32, then does a single ZeRO-1
        update.  Numerically the mean gradient of the full batch; the
        per-device batch must be divisible by it.

        ``ema_decay``: keep an exponential moving average of the flat
        params inside the jitted step (``ema = d*ema + (1-d)*params``, the
        ImageNet/TPU recipe); read it with ``get_variables(ema=True)``.

        ``seq_parallel``: additionally shard the SEQUENCE dimension (dim 1
        of every rank>=2 input/target) over the mesh's "seq" axis — the
        long-context training path.  The model's attention layers must be
        sequence-parallel-aware (``MultiHeadAttention(seq_parallel="ring"
        |"ulysses")``); position-wise layers need no change.  Per-block
        gradients are pmean'd over the seq axis before the ZeRO-1 cycle;
        losses/targets must be per-token means so block means compose
        (every block has equal token counts).  The jitted step is built
        lazily on the first batch (leaf ranks decide which dims shard)."""
        self.model = model
        self.criterion = criterion
        self.optim = optim_method
        self.mesh = mesh
        self.clip = clip
        if grad_comm is not None:
            # same normalization as BIGDL_TPU_GRAD_COMM: every entry
            # point (env / Optimizer attr / Estimator config) accepts
            # the same spellings
            grad_comm = str(grad_comm).strip().lower()
        if bf16_grads:
            warnings.warn(
                "bf16_grads is deprecated: use grad_comm='bf16' "
                "(docs/parallelism.md §Gradient compression)",
                DeprecationWarning, stacklevel=2)
            if grad_comm is None:
                grad_comm = "bf16"
        if grad_comm is None:
            grad_comm = "fp32"
        if grad_comm not in collectives.GRAD_COMM_MODES:
            raise ValueError(f"grad_comm {grad_comm!r}: one of "
                             f"{collectives.GRAD_COMM_MODES}")
        self.grad_comm = grad_comm
        if param_comm is not None:
            param_comm = str(param_comm).strip().lower()
        if param_comm is None:
            param_comm = "fp32"
        if param_comm not in collectives.PARAM_COMM_MODES:
            raise ValueError(f"param_comm {param_comm!r}: one of "
                             f"{collectives.PARAM_COMM_MODES}")
        self.param_comm = param_comm
        # legacy readers (benches, old ledgers): True exactly for bf16 wire
        self.bf16_grads = grad_comm == "bf16"
        self.quant_block = int(quant_block)
        self.comm_bucket_bytes = comm_bucket_bytes
        self.remat = remat
        # selective rematerialization: keep the MXU outputs (matmul/conv
        # results — expensive to recompute, cheap to store) and recompute
        # only the fused elementwise tail.  "dots": jax's
        # dots_with_no_batch_dims_saveable policy (the standard long-
        # context recipe); "nothing": recompute everything (max memory
        # savings); None: jax default (= nothing saveable).
        if remat_policy in (None, "nothing"):
            self.remat_policy = None
        elif remat_policy == "dots":
            self.remat_policy = (jax.checkpoint_policies
                                 .dots_with_no_batch_dims_saveable)
        elif callable(remat_policy):
            self.remat_policy = remat_policy
        else:
            raise ValueError(
                f"remat_policy {remat_policy!r}: None | 'nothing' | 'dots' "
                "| a jax.checkpoint_policies callable")
        self.accum_steps = int(accum_steps)
        self.ema_decay = float(ema_decay)
        # ICI (within-slice) data axis: the ZeRO-1 shard denominator.  A
        # multislice mesh adds an outer "dcn_data" axis; gradients
        # reduce-scatter over ICI first and only 1/ndev of the vector
        # crosses DCN (hierarchical allreduce — BASELINE.md 8->256 target).
        axes = dict(mesh.shape)
        self.ndev = axes[AXIS_DATA]
        self.dcn = axes.get(AXIS_DCN, 1)
        self._dcn_axis = AXIS_DCN if self.dcn > 1 else None
        self._batch_axes = ((AXIS_DCN, AXIS_DATA) if AXIS_DCN in axes
                            else (AXIS_DATA,))
        self.n_seq = axes.get(AXIS_SEQ, 1)
        self.seq_parallel = bool(seq_parallel)
        if self.seq_parallel:
            if self.n_seq <= 1:
                raise ValueError(
                    "seq_parallel needs a mesh seq axis > 1 "
                    "(init_engine(seq=N))")
            _check_seq_parallel_model(model)

        flat, self.unravel = ravel_pytree(init_variables["params"])
        self.n_real = flat.shape[0]
        self.n_pad = -(-self.n_real // self.ndev) * self.ndev
        self.shard_size = self.n_pad // self.ndev
        # gradient-sync bucket table: contiguous column ranges of the
        # (ndev, shard_size) gradient view — one collective per bucket,
        # ownership identical to the monolithic layout for any bucketing
        self._bucket_cols = collectives.bucket_columns(
            self.shard_size, self.ndev, comm_bucket_bytes,
            collectives.wire_itemsize(self.grad_comm),
            self.quant_block if self.grad_comm == "int8" else None)

        # partial-training mask (LoRA / linear probe / freezing): a pytree
        # matching params with bool leaves (per-leaf scalars, e.g.
        # nn.lora.lora_filter, or per-element arrays).  Frozen entries get
        # zero gradient (optimizer moments stay clean) AND are restored
        # bitwise after the update (weight decay cannot drift them).
        self._mask_flat = None
        if trainable_mask is not None:
            import numpy as _np

            leaves_p = jax.tree_util.tree_leaves(init_variables["params"])
            leaves_m = jax.tree_util.tree_leaves(trainable_mask)
            if len(leaves_p) != len(leaves_m):
                raise ValueError(
                    "trainable_mask structure does not match params "
                    f"({len(leaves_m)} leaves vs {len(leaves_p)})")
            parts = [_np.broadcast_to(
                _np.asarray(m, bool), _np.shape(p)).reshape(-1)
                for p, m in zip(leaves_p, leaves_m)]
            mask = _np.concatenate(parts).astype(_np.float32)
            self._mask_flat = jnp.pad(jnp.asarray(mask),
                                      (0, self.n_pad - self.n_real))

        self._rep = NamedSharding(mesh, P())
        self._sharded_vec = NamedSharding(mesh, P(AXIS_DATA))
        self._batch_sh = NamedSharding(mesh, P(self._batch_axes))

        # initial device state
        self.flat_params = jax.device_put(
            jnp.pad(flat, (0, self.n_pad - self.n_real)), self._rep)
        self.model_state = jax.device_put(init_variables.get("state", {}),
                                          self._rep)
        # jnp.copy: device_put of an already-placed array is a no-op and
        # would ALIAS ema to flat_params (double donation)
        self.ema_flat = (jax.device_put(jnp.copy(self.flat_params),
                                        self._rep)
                         if self.ema_decay else None)
        # EMA disabled: a distinct 1-element buffer rides the donated slot
        # (donating flat_params twice is an XLA error); it is re-captured
        # from the step output each iteration (donation aliases it through)
        self._ema_dummy = (None if self.ema_decay else
                           jax.device_put(jnp.zeros((1,), flat.dtype),
                                          self._rep))
        if self.optim.elementwise:
            opt_state = self.optim.init_state(jnp.zeros((self.n_pad,), flat.dtype))
            if len(self._bucket_cols) > 1:
                # per-bucket updates slice every state leaf like the
                # param slice; a leaf that is NOT per-element (scalar
                # running stats, oddly-shaped extras) would be fed whole
                # to every bucket and silently diverge from the
                # monolithic trajectory — fail loudly instead
                bad = [tuple(jnp.shape(l)) for l in
                       jax.tree_util.tree_leaves(opt_state)
                       if tuple(jnp.shape(l)) != (self.n_pad,)]
                if bad:
                    raise ValueError(
                        "comm_bucket_bytes requires per-element "
                        "optimizer state (every leaf shaped "
                        f"({self.n_pad},)); {type(self.optim).__name__} "
                        f"has leaves shaped {bad} — use "
                        "comm_bucket_bytes=None with this OptimMethod")
            self.opt_state = jax.device_put(opt_state, self._sharded_vec)
        else:
            opt_state = self.optim.init_state(init_variables["params"])
            self.opt_state = jax.device_put(opt_state, self._rep)
        # host-side structure templates for checkpoint load (safe to use even
        # when device buffers were consumed by a failed donated step)
        _z = lambda t: jax.tree_util.tree_map(
            lambda x: np.zeros(jnp.shape(x), jnp.asarray(x).dtype), t)
        self.opt_template = _z(opt_state)
        self.model_state_template = _z(init_variables.get("state", {}))

        # seq_parallel specs depend on leaf ranks (which dims shard), so
        # the jitted step is built lazily on the first batch
        self._train = None if self.seq_parallel else self._build_train()
        self._eval_cache: Dict[Any, Callable] = {}
        # fused multi-step programs, one per distinct bundle size (the
        # driver's remainder bundles compile once per K' and are reused)
        self._bundle_cache: Dict[Any, Callable] = {}
        self._base_key = None  # set_step_seed: device-resident PRNG root

    # ------------------------------------------------------------------
    def _leaf_spec(self, a) -> P:
        """Batch sharding spec for one input/target leaf: dim 0 over the
        data axes, dim 1 over the seq axis when sequence-parallel and the
        leaf carries a sequence dimension."""
        if self.seq_parallel and jnp.ndim(a) >= 2:
            return P(self._batch_axes, AXIS_SEQ)
        return P(self._batch_axes)

    def _leaf_sharding(self, a) -> NamedSharding:
        # only two distinct shardings exist; cache them off the hot path
        if self.seq_parallel and jnp.ndim(a) >= 2:
            sh = getattr(self, "_batch_seq_sh", None)
            if sh is None:
                sh = self._batch_seq_sh = NamedSharding(
                    self.mesh, P(self._batch_axes, AXIS_SEQ))
            return sh
        return self._batch_sh

    def _batch_specs(self, tree):
        return jax.tree_util.tree_map(self._leaf_spec, tree)

    # ------------------------------------------------------------------
    def _make_step_shard(self, want_gnorm: bool = False, comm: bool = True):
        """The single-step body shared by the classic one-step program and
        the K-step bundle: (flat_p, ema, opt_state, mstate, step, rng, x,
        y, mask) -> (new_flat, new_ema, new_opt, new_mstate, loss, gnorm).
        ``want_gnorm`` adds the global mean-gradient L2 norm (one extra
        scalar psum on the elementwise path); without it the slot is a
        constant 0 so the classic program's collectives are unchanged.
        ``comm=False`` builds the compute-only overlap-audit variant:
        the gradient scatter / param gather are replaced by same-shaped
        local ops (WRONG numerics; model fwd/bwd and update FLOPs are
        preserved, but the wire codec — int8 quantize/dequantize, bf16
        casts — is elided with the collectives, so the audit attributes
        codec cost to the collective side, matching the comm-only
        probe's denominator) so :meth:`measure_overlap` can time the
        step without its collectives."""
        model, criterion, optim = self.model, self.criterion, self.optim
        unravel, n_real = self.unravel, self.n_real
        ndev, shard_size = self.ndev, self.shard_size
        clip = self.clip
        elementwise = optim.elementwise
        remat = self.remat
        grad_comm, quant_block = self.grad_comm, self.quant_block
        param_comm = self.param_comm
        bucket_cols = tuple(self._bucket_cols)
        dcn = self.dcn
        remat_policy = self.remat_policy
        accum = max(1, self.accum_steps)
        ema_decay = self.ema_decay

        dcn_axis, n_replicas = self._dcn_axis, self.ndev * self.dcn
        batch_axes = self._batch_axes
        seq_par = self.seq_parallel
        # axes every per-block statistic (loss, model state, layerwise
        # grads) averages over
        stat_axes = batch_axes + ((AXIS_SEQ,) if seq_par else ())

        def step_shard(flat_p, ema, opt_state, mstate, step, rng, x, y,
                       mask):
            # mask: trainable-mask vector (n_pad,) — or the scalar 1.0
            # when everything trains (broadcast no-op)
            params = unravel(flat_p[:n_real])
            replica = jax.lax.axis_index(AXIS_DATA)
            if dcn_axis:
                replica = replica + ndev * jax.lax.axis_index(dcn_axis)
            if seq_par:
                replica = (replica * axis_size(AXIS_SEQ)
                           + jax.lax.axis_index(AXIS_SEQ))
            dev_rng = jax.random.fold_in(rng, replica)

            def grad_of(p, ms, xs_mb, y_mb, rng_mb):
                def loss_fn(pp):
                    out, new_ms = model.forward(
                        pp, ms, *xs_mb, training=True, rng=rng_mb)
                    return criterion.forward(out, y_mb), new_ms

                if remat:
                    loss_fn = jax.checkpoint(loss_fn, policy=remat_policy)
                return jax.value_and_grad(loss_fn, has_aux=True)(p)

            if accum == 1:
                (loss, new_mstate), grads = grad_of(
                    params, mstate, as_inputs(x), y, dev_rng)
                flat_g, _ = ravel_pytree(grads)
            else:
                # microbatch scan: one microbatch's activations live at a
                # time; flat f32 gradient accumulates across iterations
                def split(a):
                    return a.reshape((accum, a.shape[0] // accum)
                                     + a.shape[1:])

                xs_s = tuple(split(a) for a in as_inputs(x))
                y_s = split(y)

                def micro(carry, inp):
                    ms_c, gsum, lsum, k = carry
                    xs_mb = inp[:-1]
                    y_mb = inp[-1]
                    rng_mb = jax.random.fold_in(dev_rng, k)
                    (l, new_ms), grads = grad_of(params, ms_c, xs_mb, y_mb,
                                                 rng_mb)
                    fg, _ = ravel_pytree(grads)
                    return (new_ms, gsum + fg.astype(jnp.float32),
                            lsum + l, k + 1), None

                gsum0 = jnp.zeros((n_real,), jnp.float32)
                (new_mstate, gsum, lsum, _), _ = jax.lax.scan(
                    micro, (mstate, gsum0, jnp.asarray(0.0, jnp.float32),
                            jnp.asarray(0, jnp.int32)),
                    xs_s + (y_s,))
                flat_g = gsum / accum
                loss = lsum / accum
            if seq_par:
                # per-sequence-block grads average over the seq axis (the
                # loss is a per-token mean, blocks are equal-sized); params
                # stay replicated across seq so the ZeRO cycle below only
                # spans the data axes
                flat_g = jax.lax.pmean(flat_g, AXIS_SEQ)
            flat_g = jnp.pad(flat_g, (0, flat_p.shape[0] - n_real))
            # frozen entries: zero gradient (keeps optimizer moments clean)
            flat_g = flat_g * mask.astype(flat_g.dtype)

            if elementwise:
                # bucketed reduce-scatter (mean) -> sharded update ->
                # all-gather: exactly AllReduceParameter's
                # put/aggregate/send cycle, one collective per bucket so
                # XLA can overlap a bucket's update/gather with its
                # neighbours' scatter (docs/parallelism.md §Gradient
                # compression & bucketed overlap).  Wire format per
                # grad_comm: f32 / bf16 psum_scatter, or blockwise-int8
                # all_to_all summed in a widened f32 accumulator.
                # Multislice: scatter rides ICI first, then only the
                # 1/ndev slice crosses DCN (quantized again under int8);
                # every slice computes the identical update, so no
                # parameter bytes cross DCN.
                rank = jax.lax.axis_index(AXIS_DATA)
                g2d = (flat_g.reshape(ndev, shard_size) if ndev > 1
                       else None)
                slices = []
                for c0, c1 in bucket_cols:
                    if ndev > 1 and comm:
                        sb = collectives.reduce_scatter_wire(
                            g2d[:, c0:c1], AXIS_DATA, grad_comm,
                            block=quant_block)
                    elif ndev > 1:  # comm=False overlap probe: local chunk
                        sb = jax.lax.dynamic_slice(
                            flat_g, (rank * shard_size + c0,), (c1 - c0,))
                    else:
                        # single-rank data axis: no wire, no quantization
                        sb = flat_g[c0:c1]
                    if dcn_axis and comm:
                        # still in the gradient dtype: with bf16 the DCN
                        # hop carries half the bytes; int8 runs the
                        # two-phase quantized exchange
                        sb = collectives.psum_wire(
                            sb, dcn_axis, dcn, grad_comm,
                            block=quant_block)
                    slices.append(sb.astype(jnp.float32) / n_replicas)
                sq_local = sum(jnp.sum(sb * sb) for sb in slices)
                gnorm = (jnp.sqrt(jax.lax.psum(sq_local, AXIS_DATA))
                         if want_gnorm else jnp.asarray(0.0, jnp.float32))
                if clip is not None:
                    if (clip.constant_min is not None
                            or clip.constant_max is not None):
                        slices = [jnp.clip(sb, clip.constant_min,
                                           clip.constant_max)
                                  for sb in slices]
                    if clip.l2_norm is not None:
                        # global norm over the full (sharded) gradient
                        sq = jax.lax.psum(
                            sum(jnp.sum(sb * sb) for sb in slices),
                            AXIS_DATA)
                        scale = jnp.minimum(
                            1.0, clip.l2_norm / (jnp.sqrt(sq) + 1e-12))
                        slices = [sb * scale for sb in slices]

                def slice_state(leaf, c0, wb):
                    a = jnp.asarray(leaf)
                    if a.ndim >= 1 and a.shape[0] == shard_size:
                        return jax.lax.dynamic_slice_in_dim(a, c0, wb, 0)
                    return a

                new_parts, opt_parts = [], []
                for (c0, c1), sb in zip(bucket_cols, slices):
                    wb = c1 - c0
                    p_b = jax.lax.dynamic_slice(
                        flat_p, (rank * shard_size + c0,), (wb,))
                    o_b = (opt_state if len(bucket_cols) == 1 else
                           jax.tree_util.tree_map(
                               lambda l, c=c0, w=wb: slice_state(l, c, w),
                               opt_state))
                    np_b, no_b = optim.update(step, sb, p_b, o_b)
                    if ndev > 1 and comm:
                        if param_comm == "int8":
                            # delta gather: int8 payload + scales are
                            # identical bytes on every rank, the base
                            # rows come from the replicated flat_p —
                            # params stay bit-identical replicated
                            base = flat_p.reshape(
                                ndev, shard_size)[:, c0:c1]
                            np_b = collectives.all_gather_delta_quantized(
                                np_b - p_b, base, AXIS_DATA,
                                block=quant_block).reshape(-1)
                        else:
                            np_b = jax.lax.all_gather(
                                np_b, AXIS_DATA, tiled=True)
                    elif ndev > 1:  # comm=False probe: same-shape local op
                        np_b = jnp.tile(np_b, ndev)
                    new_parts.append(np_b.reshape(max(ndev, 1), wb))
                    opt_parts.append(no_b)
                # bucket b's gather returns columns [c0,c1) of every
                # rank's chunk; concat along columns rebuilds the
                # monolithic (ndev, shard_size) layout
                new_flat = jnp.concatenate(new_parts, axis=1).reshape(-1)
                if len(opt_parts) == 1:
                    new_opt = opt_parts[0]
                else:
                    def join_state(*parts):
                        a0 = jnp.asarray(parts[0])
                        if a0.ndim >= 1 and sum(
                                jnp.shape(p)[0] for p in parts) \
                                == shard_size:
                            return jnp.concatenate(parts, axis=0)
                        return parts[-1]  # unsliced leaf: buckets agree

                    new_opt = jax.tree_util.tree_map(
                        join_state, *opt_parts)
            else:
                # layerwise methods (LARS): plain psum allreduce + replicated
                # update (matches the reference's treatment pre-slice-
                # sharding); grad_comm is an elementwise-cycle knob, so
                # this path always syncs full precision.  Re-tree the flat
                # (masked) gradient so the trainable_mask reaches this
                # path's optimizer update too
                grads = unravel(flat_g[:n_real].astype(jnp.float32))
                grads = jax.tree_util.tree_map(
                    lambda g: jax.lax.pmean(g, batch_axes), grads)
                if want_gnorm:
                    fg_n, _ = ravel_pytree(grads)
                    gnorm = jnp.linalg.norm(fg_n)
                else:
                    gnorm = jnp.asarray(0.0, jnp.float32)
                if clip is not None and clip.l2_norm is not None:
                    fg, _ = ravel_pytree(grads)
                    norm = jnp.linalg.norm(fg)
                    scale = jnp.minimum(1.0, clip.l2_norm / (norm + 1e-12))
                    grads = jax.tree_util.tree_map(lambda g: g * scale, grads)
                new_params, new_opt = optim.update(step, grads, params, opt_state)
                nf, _ = ravel_pytree(new_params)
                new_flat = jnp.pad(nf, (0, flat_p.shape[0] - n_real))

            # restore frozen entries bitwise: weight decay / bias-corrected
            # moments must not drift parameters that carry no gradient
            new_flat = jnp.where(mask > 0, new_flat, flat_p)
            loss = jax.lax.pmean(loss, stat_axes)
            new_mstate = jax.tree_util.tree_map(
                lambda a: jax.lax.pmean(a, stat_axes)
                if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating) else a,
                new_mstate)
            new_ema = (ema_decay * ema + (1.0 - ema_decay) * new_flat
                       if ema_decay else ema)
            return new_flat, new_ema, new_opt, new_mstate, loss, gnorm

        return step_shard

    def _train_specs(self, x_ex=None, y_ex=None):
        """(opt_spec, x_spec, y_spec) for the train programs — seq_parallel
        specs depend on leaf ranks, so they need example batches."""
        opt_spec = (P(AXIS_DATA) if self.optim.elementwise else P())
        if self.seq_parallel:
            x_spec = self._batch_specs(x_ex)
            y_spec = self._batch_specs(y_ex)
        else:
            x_spec = y_spec = P(self._batch_axes)
        return opt_spec, x_spec, y_spec

    def _build_train(self, x_ex=None, y_ex=None, donate: bool = True,
                     comm: bool = True):
        core = self._make_step_shard(want_gnorm=False, comm=comm)

        def step_shard(flat_p, ema, opt_state, mstate, step, rng, x, y,
                       mask):
            return core(flat_p, ema, opt_state, mstate, step, rng, x, y,
                        mask)[:5]

        opt_spec, x_spec, y_spec = self._train_specs(x_ex, y_ex)
        mapped = shard_map(
            step_shard, mesh=self.mesh,
            in_specs=(P(), P(), opt_spec, P(), P(), P(), x_spec, y_spec,
                      P()),
            out_specs=(P(), P(), opt_spec, P(), P()),
        )
        if not donate:  # overlap-audit probes must not consume live state
            return jax.jit(mapped)
        return jax.jit(mapped, donate_argnums=(0, 1, 2, 3))

    def _build_bundle(self, n_steps: int, x_ex=None, y_ex=None):
        """K consecutive training steps as ONE jitted XLA program: a
        ``lax.scan`` whose body is exactly the single-step shard function,
        loop-carrying (params, EMA, opt-state, model-state, step counter)
        with donation across the whole bundle.  Per-step PRNG derives from
        the ON-DEVICE step counter (``fold_in(base_key, step)``) and the LR
        schedule evaluates on device inside each update, so the host does
        zero per-step work between bundle edges.  Returns length-K loss and
        grad-norm vectors so per-step granularity (NaN-streak detection,
        loss curves) survives bundling.

        The K input batches arrive as a K-tuple of ordinary per-batch
        device arrays (each sharded exactly like the single-step program's
        batch) and are stacked PER DEVICE inside the shard: the scan xs is
        assembled from local shards, so no host-side super-batch copy and
        no resharding collective ever happens."""
        core = self._make_step_shard(want_gnorm=True)

        def bundle_shard(flat_p, ema, opt_state, mstate, step0, base_key,
                         xs, ys, mask):
            x_stack = jax.tree_util.tree_map(
                lambda *ls: jnp.stack(ls), *xs)
            y_stack = jax.tree_util.tree_map(
                lambda *ls: jnp.stack(ls), *ys)

            def body(carry, xy):
                fp, em, op, ms, step = carry
                x_k, y_k = xy
                rng = jax.random.fold_in(base_key, step)
                nf, ne, no, nm, loss, gnorm = core(
                    fp, em, op, ms, step, rng, x_k, y_k, mask)
                return (nf, ne, no, nm, step + 1), (loss, gnorm)

            (flat_p, ema, opt_state, mstate, _), (losses, gnorms) = \
                jax.lax.scan(body,
                             (flat_p, ema, opt_state, mstate, step0),
                             (x_stack, y_stack))
            return flat_p, ema, opt_state, mstate, losses, gnorms

        opt_spec, x_spec, y_spec = self._train_specs(x_ex, y_ex)
        xs_spec = (tuple(x_spec for _ in range(n_steps))
                   if self.seq_parallel else x_spec)
        ys_spec = (tuple(y_spec for _ in range(n_steps))
                   if self.seq_parallel else y_spec)
        mapped = shard_map(
            bundle_shard, mesh=self.mesh,
            in_specs=(P(), P(), opt_spec, P(), P(), P(), xs_spec, ys_spec,
                      P()),
            out_specs=(P(), P(), opt_spec, P(), P(), P()),
        )
        return jax.jit(mapped, donate_argnums=(0, 1, 2, 3))

    # ------------------------------------------------------------------
    def _build_eval(self, methods: Tuple, x_ex=None, y_ex=None, w_ex=None):
        model, unravel, n_real = self.model, self.unravel, self.n_real

        # seq_parallel models MUST see seq-sharded inputs in eval too (their
        # attention layers run seq collectives unconditionally); stats then
        # sum over the seq axis as well — correct for per-token metrics
        stat_axes = self._batch_axes + ((AXIS_SEQ,)
                                        if self.seq_parallel else ())

        def eval_shard(flat_p, mstate, x, y, w):
            params = unravel(flat_p[:n_real])
            xs = as_inputs(x)
            out, _ = model.forward(params, mstate, *xs, training=False)
            stats = []
            for m in methods:
                s, c = m.batch_stats(out, y, w)
                stats.append((jax.lax.psum(s, stat_axes),
                              jax.lax.psum(c, stat_axes)))
            return tuple(stats)

        if self.seq_parallel:
            x_spec = self._batch_specs(x_ex)
            y_spec = self._batch_specs(y_ex)
            w_spec = self._batch_specs(w_ex)
        else:
            x_spec = y_spec = w_spec = P(self._batch_axes)
        mapped = shard_map(
            eval_shard, mesh=self.mesh,
            in_specs=(P(), P(), x_spec, y_spec, w_spec),
            out_specs=P())
        return jax.jit(mapped)

    @property
    def comm_buckets(self) -> int:
        """Number of gradient-sync buckets (1 = monolithic transfer)."""
        return len(self._bucket_cols)

    @property
    def grad_sync_ici_bytes_per_step(self) -> int:
        """Per-step ICI wire bytes of the GRADIENT reduce-scatter, in the
        actual wire dtype: f32/bf16 payload, or int8 payload + f32
        per-block scales (block padding included) under
        ``grad_comm="int8"`` — the honest before/after meter for
        compression work (``parallel.collectives`` estimators are the
        source of truth)."""
        if self.ndev <= 1:
            return 0
        return sum(collectives.rs_wire_bytes(
            c1 - c0, self.ndev, self.grad_comm, self.quant_block)
            for c0, c1 in self._bucket_cols)

    @property
    def param_sync_ici_bytes_per_step(self) -> int:
        """Per-step ICI wire bytes of the updated-param all_gather, in
        the ACTUAL ``param_comm`` wire dtype: f32 gather bytes
        (``n_pad * 4``) by default; int8 delta payload + f32 per-block
        scales under ``param_comm="int8"``."""
        if self.ndev <= 1:
            return 0
        return sum(collectives.ag_wire_bytes(
            c1 - c0, self.ndev, self.param_comm, self.quant_block)
            for c0, c1 in self._bucket_cols)

    @property
    def collective_bytes_per_step(self) -> int:
        """Per-step ICI traffic of the ZeRO-1 cycle: the gradient
        reduce-scatter (wire dtype per ``grad_comm``, scales included) +
        all_gather of the updated flat f32 params.  Zero on a
        single-device axis — a size-1 collective moves no bytes (matches
        ``gspmd.collective_bytes_for_specs`` for the same topology)."""
        return (self.grad_sync_ici_bytes_per_step
                + self.param_sync_ici_bytes_per_step)

    @property
    def n_data_replicas(self) -> int:
        """Total data-parallel degree (ICI x DCN) — batch dim multiples."""
        return self.ndev * self.dcn

    @property
    def dcn_bytes_per_step(self) -> int:
        """Per-step CROSS-SLICE (DCN) traffic: the hierarchical allreduce
        moves only the 1/ndev gradient slice over DCN (psum ~ 2x slice
        bytes, in the ``grad_comm`` wire dtype — int8 counts payload +
        scales for both quantized phases); parameters never cross
        slices."""
        if self.dcn <= 1:
            return 0
        return sum(collectives.psum_wire_bytes(
            c1 - c0, self.dcn, self.grad_comm, self.quant_block)
            for c0, c1 in self._bucket_cols)

    # -- overlap audit (docs/performance.md §Gradient-comm modes) -------
    def _build_comm_probe(self):
        """Comm-only program: ONLY the bucketed gradient reduce-scatter
        (+ DCN hop) and the bucketed param all_gather, on same-shaped
        vectors — what :meth:`measure_overlap` times as 'total collective
        time'."""
        ndev, shard_size, dcn = self.ndev, self.shard_size, self.dcn
        dcn_axis = self._dcn_axis
        grad_comm, block = self.grad_comm, self.quant_block
        param_comm = self.param_comm
        cols = tuple(self._bucket_cols)
        batch_axes = self._batch_axes

        def comm_shard(flat_g, flat_p):
            rank = jax.lax.axis_index(AXIS_DATA)
            acc = jnp.asarray(0.0, jnp.float32)
            g2d = flat_g.reshape(ndev, shard_size) if ndev > 1 else None
            for c0, c1 in cols:
                wb = c1 - c0
                if ndev > 1:
                    # the SAME wire dispatch the step body uses — the
                    # audit must time exactly the step's collectives
                    sb = collectives.reduce_scatter_wire(
                        g2d[:, c0:c1], AXIS_DATA, grad_comm, block=block)
                else:
                    sb = flat_g[c0:c1]
                if dcn_axis:
                    sb = collectives.psum_wire(sb, dcn_axis, dcn,
                                               grad_comm, block=block)
                acc = acc + jnp.sum(sb.astype(jnp.float32))
                p_b = jax.lax.dynamic_slice(
                    flat_p, (rank * shard_size + c0,), (wb,))
                if ndev > 1 and param_comm == "int8":
                    # same wire shape as the step's delta gather (int8
                    # payload + scales); p_b stands in for the delta —
                    # the probe only needs byte-identical collectives
                    base = flat_p.reshape(ndev, shard_size)[:, c0:c1]
                    p_b = collectives.all_gather_delta_quantized(
                        p_b, base, AXIS_DATA, block=block)
                elif ndev > 1:
                    p_b = jax.lax.all_gather(p_b, AXIS_DATA, tiled=True)
                acc = acc + jnp.sum(p_b)
            # replicate the scalar so the out_spec holds on every rank
            return jax.lax.pmean(acc, batch_axes)

        mapped = shard_map(comm_shard, mesh=self.mesh,
                           in_specs=(P(), P()), out_specs=P())
        return jax.jit(mapped)

    def measure_overlap(self, x_dev, y_dev, *, steps: int = 5,
                        rng=None) -> Dict[str, float]:
        """One-shot overlap audit: how much of the gradient-sync
        collective time does the step structure hide under compute?

        Times three programs on the SAME shapes — the real train step, a
        compute-only variant (collectives replaced by same-shaped local
        ops), and a comm-only probe (just the bucketed scatter/gather
        cycle) — and reports::

            exposed_collective_s = max(0, step_s - compute_s)
            overlap_efficiency   = 1 - exposed / collective_s   (in [0,1])

        Builds two extra non-donating XLA programs, so this is a
        bench/audit call (``bench_scaling --grad-comm``,
        ``BIGDL_TPU_MEASURE_OVERLAP=1``), not a hot-path one.  Training
        state is read, never consumed."""
        import time as _time

        if self.seq_parallel:
            raise NotImplementedError(
                "overlap audit under seq_parallel: use bench_scaling on "
                "a data-parallel mesh")
        if rng is None:
            rng = jax.random.PRNGKey(0)
        ema_in = self.ema_flat if self.ema_flat is not None \
            else self._ema_dummy
        mask_in = (self._mask_flat if self._mask_flat is not None
                   else jnp.asarray(1.0, jnp.float32))
        full = self._build_train(donate=False)
        nocomm = self._build_train(donate=False, comm=False)
        probe = self._build_comm_probe()
        args = (self.flat_params, ema_in, self.opt_state,
                self.model_state, jnp.asarray(0, jnp.int32), rng,
                x_dev, y_dev, mask_in)

        def timed(fn, *a):
            jax.block_until_ready(fn(*a))  # compile + warm
            ts = []
            for _ in range(max(1, steps)):
                t0 = _time.perf_counter()
                jax.block_until_ready(fn(*a))
                ts.append(_time.perf_counter() - t0)
            return float(np.median(ts))

        with expected_compile():
            t_full = timed(full, *args)
            t_nocomm = timed(nocomm, *args)
            t_comm = timed(probe, self.flat_params, self.flat_params)
        exposed = max(0.0, t_full - t_nocomm)
        eff = (min(1.0, max(0.0, 1.0 - exposed / t_comm))
               if t_comm > 0 else 0.0)
        return {"step_s": t_full, "compute_s": t_nocomm,
                "collective_s": t_comm, "exposed_collective_s": exposed,
                "overlap_efficiency": eff,
                "comm_buckets": float(len(self._bucket_cols)),
                "grad_comm": self.grad_comm}

    # ------------------------------------------------------------------
    def shard_batch(self, arr):
        """Host numpy (per-process shard) -> global device array on the data
        axis (and the seq axis for rank>=2 leaves when sequence-parallel).
        Accepts a pytree (tuple of arrays for multi-input models)."""
        if jax.process_count() == 1:
            return jax.tree_util.tree_map(
                lambda a: jax.device_put(a, self._leaf_sharding(a)), arr)
        return jax.tree_util.tree_map(
            lambda a: jax.make_array_from_process_local_data(
                self._leaf_sharding(a), a), arr)

    def train_step(self, step: int, rng, x, y):
        return self.train_step_device(
            step, rng, self.shard_batch(x), self.shard_batch(y))

    def train_step_device(self, step: int, rng, x_dev, y_dev):
        """Variant taking already-sharded device arrays (the prefetch path —
        see ``bigdl_tpu.data.prefetch``)."""
        if self._train is None:  # seq_parallel: specs need leaf ranks
            self._train = self._build_train(x_dev, y_dev)
        ema_in = self.ema_flat if self.ema_flat is not None \
            else self._ema_dummy
        mask_in = (self._mask_flat if self._mask_flat is not None
                   else jnp.asarray(1.0, jnp.float32))
        (self.flat_params, new_ema, self.opt_state, self.model_state,
         loss) = self._train(
            self.flat_params, ema_in, self.opt_state, self.model_state,
            jnp.asarray(step, jnp.int32), rng, x_dev, y_dev, mask_in)
        if self.ema_flat is not None:
            self.ema_flat = new_ema
        else:
            self._ema_dummy = new_ema
        return loss

    # -- fused multi-step execution (docs/performance.md) ---------------
    def set_step_seed(self, seed: int) -> None:
        """Place the per-run PRNG root on device ONCE; every bundled step
        derives its key inside the jitted program from the on-device step
        counter, so no host-side ``PRNGKey``/``fold_in`` runs per step.
        (put_sharded: a bare device_put of a replicated array broadcasts
        under multi-controller, which multi-host CPU meshes cannot do.)"""
        self._base_key = put_sharded(
            np.asarray(jax.random.PRNGKey(seed)), self._rep)

    def train_bundle_device(self, step0: int, xs, ys, base_key=None):
        """Run ``len(xs)`` consecutive training steps as ONE dispatched XLA
        program over already-sharded device batches.  Returns
        ``(losses, grad_norms)`` — length-K device vectors, one entry per
        step, fetched lazily by the caller.

        Numerics are identical for every bundle size: the scan body is the
        same per-step HLO, per-step PRNG is ``fold_in(base_key, step)`` of
        the global step counter, and batches keep their identities — so a
        K=4 trajectory is byte-identical to K=1 (tests/test_step_bundle)."""
        k = len(xs)
        if k == 0 or len(ys) != k:
            raise ValueError(f"bundle needs matching non-empty batch "
                             f"lists, got {k} inputs / {len(ys)} targets")
        if base_key is None:
            base_key = self._base_key
            if base_key is None:
                raise ValueError(
                    "train_bundle_device needs set_step_seed() first "
                    "(or an explicit base_key)")
        key = k
        if self.seq_parallel:
            # baked in_specs depend on leaf ranks
            key = (k, tuple(jnp.ndim(a) for a in
                            jax.tree_util.tree_leaves((xs[0], ys[0]))))
        fn = self._bundle_cache.get(key)
        new_program = fn is None
        if new_program:
            fn = self._bundle_cache[key] = self._build_bundle(
                k, xs[0], ys[0])
        ema_in = self.ema_flat if self.ema_flat is not None \
            else self._ema_dummy
        mask_in = (self._mask_flat if self._mask_flat is not None
                   else jnp.asarray(1.0, jnp.float32))
        # a first-seen bundle size (epoch-tail remainder, trigger-clamped
        # span) legitimately compiles mid-run: announce it so the
        # recompilation sentinel only flags true cache misses
        with expected_compile() if new_program else nullcontext():
            (self.flat_params, new_ema, self.opt_state, self.model_state,
             losses, gnorms) = fn(
                self.flat_params, ema_in, self.opt_state, self.model_state,
                jnp.asarray(step0, jnp.int32), base_key,
                tuple(xs), tuple(ys), mask_in)
        if self.ema_flat is not None:
            self.ema_flat = new_ema
        else:
            self._ema_dummy = new_ema
        return losses, gnorms

    def evaluate(self, methods, batches) -> list:
        # cache key must be the method *instances* (two Loss() objects with
        # different criteria are different programs); holding them in the
        # cache keeps ids stable
        acc = StatsAccumulator()
        for mb in batches:
            x = mb["input"]
            n_rows = as_inputs(x)[0].shape[0]
            w = mb.get("weight")
            if w is None:
                w = np.ones((n_rows,), np.float32)
            # cache key: method instances AND the spec-relevant batch
            # structure (the baked in_specs depend on leaf ranks)
            ranks = tuple(np.ndim(a) for a in
                          jax.tree_util.tree_leaves((x, mb["target"], w)))
            key = (tuple(id(m) for m in methods), ranks)
            new_program = key not in self._eval_cache
            if new_program:
                # built on the first batch: seq_parallel specs need ranks
                self._eval_cache[key] = (tuple(methods), self._build_eval(
                    tuple(methods), x, mb["target"], w))
            _, fn = self._eval_cache[key]
            # a first validation pass mid-run compiles its eval program —
            # expected, not an XLA cache miss
            with expected_compile() if new_program else nullcontext():
                acc.add(fn(self.flat_params, self.model_state,
                           self.shard_batch(x),
                           self.shard_batch(mb["target"]),
                           self.shard_batch(w)))
        totals = acc.fetch()
        return [m.fold(s, c) for m, (s, c) in zip(methods, totals or [])]

    # ------------------------------------------------------------------
    def rebuild_programs(self) -> None:
        """Drop every compiled program so the next call re-traces the
        model.  Needed after HOST-side model structure changes jit cannot
        see in its input avals — e.g. a block-sparse FFN mask restored
        from a checkpoint or changed by a pruning event: the mask is a
        trace-time constant, so a stale program would keep computing with
        the old sparsity pattern."""
        self._train = None if self.seq_parallel else self._build_train()
        self._eval_cache.clear()
        self._bundle_cache.clear()
        if hasattr(self, "_predict_jit"):
            self._predict_jit = None

    def get_variables(self, ema: bool = False) -> Dict[str, Any]:
        src = self.ema_flat if (ema and self.ema_flat is not None) \
            else self.flat_params
        flat = np.asarray(src)[: self.n_real]
        return {"params": self.unravel(jnp.asarray(flat)),
                "state": jax.device_get(self.model_state)}

    def predict_fn(self):
        """Jitted inference callable over the mesh (batch data-sharded).
        The jitted forward is cached on the engine so repeated predict()
        calls don't recompile."""
        fwd = getattr(self, "_predict_jit", None)
        if fwd is None:
            model, unravel, n_real = self.model, self.unravel, self.n_real

            def raw(flat_p, mstate, x):
                params = unravel(flat_p[:n_real])
                xs = as_inputs(x)
                out, _ = model.forward(params, mstate, *xs, training=False)
                return out

            if self.seq_parallel:
                # seq-parallel attention runs seq collectives, so inference
                # too must live inside a shard_map carrying the axis; output
                # leaves must be per-token (batch, seq, ...) — pooled heads
                # are not representable under sequence sharding
                out_spec = P(self._batch_axes, AXIS_SEQ)
                mesh = self.mesh
                _cache: Dict[Any, Callable] = {}

                def fwd(flat_p, mstate, x):
                    key = jax.tree_util.tree_structure(x)
                    if key not in _cache:
                        _cache[key] = jax.jit(shard_map(
                            raw, mesh=mesh,
                            in_specs=(P(), P(), self._batch_specs(x)),
                            out_specs=out_spec))
                    return _cache[key](flat_p, mstate, x)
            else:
                fwd = jax.jit(raw)

            self._predict_jit = fwd

        if jax.process_count() > 1:
            if self.seq_parallel:
                raise NotImplementedError(
                    "multi-host predict with seq_parallel: run evaluate() "
                    "(mesh-wide) or export the params for single-host "
                    "inference")
            # multi-host: predict locally per process (params are replicated,
            # so each host can run inference on its own shard of requests
            # without building a non-addressable global output)
            host_params = np.asarray(self.flat_params)
            host_state = host_fetch(self.model_state)

            def run(x):
                return fwd(jnp.asarray(host_params), host_state,
                           jax.tree_util.tree_map(jnp.asarray, x))
        else:
            def run(x):
                return fwd(self.flat_params, self.model_state,
                           self.shard_batch(x))

        return run
