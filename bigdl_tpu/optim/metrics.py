"""Per-iteration driver metrics.

Reference analog (unverified — mount empty): ``dllib/optim/Metrics.scala`` —
named distributed counters ("computing time average", "get weights average",
"put gradient") logged per iteration by DistriOptimizer.  Under XLA the whole
iteration is one fused program, so the meaningful split is host-side: data
time (input pipeline), dispatch time (python+transfer), device step time
(block_until_ready deltas), throughput.
"""

import json
import os
import threading
import time
from collections import defaultdict
from typing import Dict, Optional

from bigdl_tpu.obs.hist import LogHistogram


def label_key(name: str, **labels) -> str:
    """Canonical registry key of a LABELED series:
    ``name{k="v",k2="v2"}`` with keys sorted and values escaped per the
    Prometheus text grammar.  The exporter (``obs.export``) splits the
    key back into family + label set, so two series of one family
    (``serving.tenant_latency_seconds{tenant="a"}`` / ``{tenant="b"}``)
    share a single ``# TYPE`` declaration in the scrape."""
    if not labels:
        return name
    parts = []
    for k in sorted(labels):
        v = str(labels[k]).replace("\\", "\\\\").replace('"', '\\"') \
            .replace("\n", "\\n")
        parts.append(f'{k}="{v}"')
    return name + "{" + ",".join(parts) + "}"


class Metrics:
    def __init__(self):
        self.sums: Dict[str, float] = defaultdict(float)
        self.counts: Dict[str, int] = defaultdict(int)
        # monotonic counters (recoveries_total, retries_by_cause.*,
        # time_lost_to_recovery_s, ...): run-lifetime totals, so they
        # survive the per-log-window reset() that clears the timers
        self.counters: Dict[str, float] = defaultdict(float)
        # latency/step-time distributions: bounded log-bucketed histograms
        # (obs.hist), run-lifetime like counters — /metrics exports their
        # p50/p95/p99 and Prometheus bucket lines
        self.hists: Dict[str, LogHistogram] = {}
        # point-in-time levels (queue depths, ring occupancy): last-write-
        # wins, exported as Prometheus gauges
        self.gauges: Dict[str, float] = {}
        # optional per-metric help strings (describe()); the exporter
        # renders them as `# HELP` lines next to `# TYPE`
        self.helps: Dict[str, str] = {}
        # the global_metrics() registry is shared across threads (serving
        # client/engine threads + the training driver); += on a dict
        # entry is a read-modify-write that loses updates without this.
        # READS hold it too: defaultdict indexing on a miss mutates, and
        # an unlocked .items() iteration races concurrent inserts
        self._lock = threading.Lock()

    def add(self, name: str, value: float):
        with self._lock:
            self.sums[name] += value
            self.counts[name] += 1

    def inc(self, name: str, n: float = 1,
            labels: Optional[Dict[str, str]] = None):
        if labels:
            name = label_key(name, **labels)
        with self._lock:
            self.counters[name] += n
        self._mirror("inc", name, n)

    def gauge(self, name: str, value: float,
              labels: Optional[Dict[str, str]] = None):
        """Set a point-in-time level (queue depth, buffer-ring occupancy);
        the scrape sees the latest value.  ``labels`` selects one series
        of a labeled family (key built by :func:`label_key`)."""
        if labels:
            name = label_key(name, **labels)
        with self._lock:
            self.gauges[name] = float(value)
        self._mirror("gauge", name, value)

    def ensure_hist(self, name: str,
                    labels: Optional[Dict[str, str]] = None,
                    **hist_kwargs) -> float:
        """Create the named histogram with explicit geometry (window_s,
        window_slices, ...) if it does not exist yet — the SLO evaluator
        pre-sizes its tenant histograms so a spec window longer than the
        default 60s ring is actually answerable.  Returns the
        histogram's (existing or created) window_s so the caller can
        detect a pre-existing smaller ring."""
        if labels:
            name = label_key(name, **labels)
        with self._lock:
            h = self.hists.get(name)
            if h is None:
                h = self.hists[name] = LogHistogram(**hist_kwargs)
            return h.window_s

    def observe(self, name: str, value: float,
                labels: Optional[Dict[str, str]] = None):
        """One sample into the named histogram (created on first use)."""
        if labels:
            name = label_key(name, **labels)
        with self._lock:
            h = self.hists.get(name)
            if h is None:
                h = self.hists[name] = LogHistogram()
            h.observe(value)
        self._mirror("observe", name, value)

    def _mirror(self, op: str, name: str, v: float) -> None:
        # run-lifetime signals (counters, histograms) recorded on a
        # per-component registry ALSO land in the process-wide one, so a
        # single /metrics scrape sees training, resilience, and serving
        # side by side without every subsystem sharing one instance.
        # Created eagerly: a counter incremented before the first scrape
        # must not be missing from it
        g = global_metrics()
        if g is not self:
            getattr(g, op)(name, v)

    def describe(self, name: str, help_text: str) -> None:
        """Attach a Prometheus ``# HELP`` string to a metric name (applies
        whatever kind the name turns out to be; mirrored like the metric
        itself so the process-wide scrape carries it too)."""
        with self._lock:
            self.helps[name] = str(help_text)
        g = global_metrics()
        if g is not self:
            g.describe(name, help_text)

    def counter(self, name: str) -> float:
        with self._lock:
            return self.counters.get(name, 0.0)

    def total(self, name: str) -> float:
        """Sum of a per-window timer (``add``) since the last ``reset`` —
        the attribution layer reads window totals, not means."""
        with self._lock:
            return self.sums.get(name, 0.0)

    def mean(self, name: str) -> float:
        with self._lock:
            c = self.counts.get(name, 0)
            return self.sums.get(name, 0.0) / c if c else 0.0

    def percentile(self, name: str, q: float) -> float:
        with self._lock:
            h = self.hists.get(name)
            return h.percentile(q) if h is not None else 0.0

    # -- sliding-window reads (SLO burn rates; docs/observability.md) -------
    def window_percentile(self, name: str, q: float,
                          labels: Optional[Dict[str, str]] = None,
                          window_s: Optional[float] = None,
                          now: Optional[float] = None) -> float:
        """q-th percentile of the histogram's trailing window; NaN when
        the window (or the histogram itself) is empty."""
        if labels:
            name = label_key(name, **labels)
        with self._lock:
            h = self.hists.get(name)
            return (h.window_percentile(q, now=now, window_s=window_s)
                    if h is not None else float("nan"))

    def window_fraction_over(self, name: str, threshold: float,
                             labels: Optional[Dict[str, str]] = None,
                             window_s: Optional[float] = None,
                             now: Optional[float] = None) -> float:
        """Fraction of window samples over ``threshold`` (NaN when the
        window is empty) — the SLO evaluator's bad-event ratio."""
        if labels:
            name = label_key(name, **labels)
        with self._lock:
            h = self.hists.get(name)
            return (h.window_fraction_over(threshold, now=now,
                                           window_s=window_s)
                    if h is not None else float("nan"))

    def window_count(self, name: str,
                     labels: Optional[Dict[str, str]] = None,
                     window_s: Optional[float] = None,
                     now: Optional[float] = None) -> int:
        if labels:
            name = label_key(name, **labels)
        with self._lock:
            h = self.hists.get(name)
            return (h.window_count(now=now, window_s=window_s)
                    if h is not None else 0)

    def reset(self):
        with self._lock:
            self.sums.clear()
            self.counts.clear()

    def summary(self) -> Dict[str, float]:
        with self._lock:
            out = {k: (self.sums[k] / self.counts[k]
                       if self.counts.get(k) else 0.0) for k in self.sums}
            out.update(self.counters)
            out.update(self.gauges)
            for k, h in self.hists.items():
                for q, v in h.quantiles().items():
                    out[f"{k}.{q}"] = v
                out[f"{k}.count"] = h.n
        return out

    def snapshot(self, blocking: bool = True) -> Optional[Dict[str, dict]]:
        """Consistent point-in-time copy of the whole registry — the
        exporter (obs.export) renders from this, never from live dicts.

        ``blocking=False`` is for signal handlers (the flight recorder's
        SIGTERM dump): the handler may have interrupted the very frame
        that holds this non-reentrant lock, so waiting would deadlock —
        return None instead and let the caller skip the snapshot."""
        if not self._lock.acquire(blocking=blocking):
            return None
        try:
            return {"sums": dict(self.sums), "counts": dict(self.counts),
                    "counters": dict(self.counters),
                    "gauges": dict(self.gauges),
                    "helps": dict(self.helps),
                    "hists": {k: h.snapshot()
                              for k, h in self.hists.items()}}
        finally:
            self._lock.release()


_GLOBAL: Optional[Metrics] = None
_GLOBAL_LOCK = threading.Lock()


def global_metrics() -> Metrics:
    """The process-wide default :class:`Metrics` registry.

    Subsystems that are not handed an explicit registry (the serving
    stack's ``serving.*`` lifecycle counters, notably) record here, so one
    ``summary()`` — and one ``/health`` scrape — sees training recovery
    counters and serving shed/expire/drain counters side by side."""
    global _GLOBAL
    if _GLOBAL is None:
        with _GLOBAL_LOCK:
            if _GLOBAL is None:
                _GLOBAL = Metrics()
    return _GLOBAL


class Timer:
    def __init__(self, metrics: Metrics, name: str):
        self.metrics = metrics
        self.name = name

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *a):
        self.metrics.add(self.name, time.perf_counter() - self.t0)


class SummaryWriter:
    """Scalar summary — the TrainSummary/ValidationSummary analog.  Writes
    BOTH jsonl (greppable primary format) and TensorBoard event protobufs
    (``utils/tbwriter.py``) so curves open in stock TensorBoard exactly as
    the reference's ``TrainSummary`` files do (SURVEY.md §6.1)."""

    def __init__(self, log_dir: str, name: str, tensorboard: bool = True):
        os.makedirs(log_dir, exist_ok=True)
        self.path = os.path.join(log_dir, f"{name}.jsonl")
        self._f = open(self.path, "a")
        self._closed = False
        self._tb = None
        if tensorboard:
            from bigdl_tpu.utils.tbwriter import TensorBoardWriter

            self._tb = TensorBoardWriter(os.path.join(log_dir, name))

    def add_scalar(self, tag: str, value: float, step: int):
        self._f.write(json.dumps(
            {"step": step, "tag": tag, "value": float(value),
             "wall": time.time()}) + "\n")
        self._f.flush()
        if self._tb is not None:
            self._tb.add_scalar(tag, value, step)

    def add_histogram(self, tag: str, values, step: int):
        if self._tb is not None:
            self._tb.add_histogram(tag, values, step)

    def read_scalar(self, tag: str):
        """(step, value) pairs for one tag — reference
        ``TrainSummary.readScalar``."""
        out = []
        with open(self.path) as f:
            for line in f:
                rec = json.loads(line)
                if rec["tag"] == tag:
                    out.append((rec["step"], rec["value"]))
        return out

    def close(self):
        """Close BOTH sinks — the jsonl file and the TensorBoard event
        writer (whose buffered tail events would otherwise be lost).
        Idempotent: the context-manager exit and an explicit close may
        both run."""
        if self._closed:
            return
        self._closed = True
        self._f.close()
        if self._tb is not None:
            self._tb.close()

    def __enter__(self) -> "SummaryWriter":
        return self

    def __exit__(self, *a) -> bool:
        self.close()
        return False


def TrainSummary(log_dir: str, app_name: str) -> SummaryWriter:
    """Reference ``utils/visualization/TrainSummary.scala`` constructor."""
    return SummaryWriter(os.path.join(log_dir, app_name), "train")


def ValidationSummary(log_dir: str, app_name: str) -> SummaryWriter:
    """Reference ``utils/visualization/ValidationSummary.scala``."""
    return SummaryWriter(os.path.join(log_dir, app_name), "validation")
