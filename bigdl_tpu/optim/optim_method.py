"""Optimization methods.

Reference analog (unverified — mount empty): ``dllib/optim/{SGD,Adam,
ParallelAdam,Adagrad,RMSprop,Ftrl,AdamWeightDecay,LarsSGD}.scala`` — each an
``OptimMethod`` with mutable internal state and
``optimize(feval, parameter)``.

TPU-native re-design: pure functions over pytrees —
``init_state(params)`` / ``update(step, grads, params, state) -> (new_params,
new_state)``.  Because they are elementwise-pytree pure functions they run
unchanged on (a) full replicated params or (b) a 1-D parameter *slice* inside
the sharded (ZeRO-1 / AllReduceParameter-style) train step.  Layer-wise
methods (LARS) set ``elementwise = False`` and require the replicated path.
"""

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.optim.schedules import Default, LearningRateSchedule

Pytree = Any


def _tmap(f, *trees):
    return jax.tree_util.tree_map(f, *trees)


class OptimMethod:
    elementwise: bool = True  # safe to run on an arbitrary 1-D slice

    def init_state(self, params: Pytree) -> Pytree:
        return {}

    def update(self, step, grads: Pytree, params: Pytree, state: Pytree):
        raise NotImplementedError

    def get_learning_rate(self, step):
        return getattr(self, "lr", 0.0)


class SGD(OptimMethod):
    """SGD with momentum/dampening/nesterov/weight-decay and pluggable LR
    schedule — reference ``optim/SGD.scala`` semantics."""

    def __init__(self, learning_rate: float = 1e-3,
                 learning_rate_decay: float = 0.0,
                 weight_decay: float = 0.0, momentum: float = 0.0,
                 dampening: Optional[float] = None, nesterov: bool = False,
                 learning_rate_schedule: Optional[LearningRateSchedule] = None):
        self.lr = learning_rate
        self.weight_decay = weight_decay
        self.momentum = momentum
        self.dampening = momentum if dampening is None else dampening
        self.nesterov = nesterov
        self.schedule = learning_rate_schedule or Default(learning_rate_decay)
        if nesterov and (momentum <= 0 or self.dampening != 0):
            # same constraint as the reference SGD
            self.dampening = 0.0

    def get_learning_rate(self, step):
        return self.schedule(self.lr, step)

    def init_state(self, params):
        if self.momentum > 0:
            return {"velocity": _tmap(jnp.zeros_like, params)}
        return {}

    def update(self, step, grads, params, state):
        lr = self.schedule(self.lr, step)
        if self.weight_decay > 0:
            grads = _tmap(lambda g, p: g + self.weight_decay * p, grads, params)
        if self.momentum > 0:
            vel = _tmap(
                lambda v, g: self.momentum * v + (1 - self.dampening) * g,
                state["velocity"], grads)
            if self.nesterov:
                grads = _tmap(lambda g, v: g + self.momentum * v, grads, vel)
            else:
                grads = vel
            state = {"velocity": vel}
        new_params = _tmap(lambda p, g: p - lr * g, params, grads)
        return new_params, state


class Adam(OptimMethod):
    """Reference ``optim/Adam.scala`` (and ``ParallelAdam`` — parallelism is
    free here: the sharded path runs the same math on slices)."""

    def __init__(self, learning_rate: float = 1e-3,
                 learning_rate_decay: float = 0.0,
                 beta1: float = 0.9, beta2: float = 0.999, epsilon: float = 1e-8,
                 learning_rate_schedule: Optional[LearningRateSchedule] = None):
        self.lr = learning_rate
        self.beta1, self.beta2, self.eps = beta1, beta2, epsilon
        self.schedule = learning_rate_schedule or Default(learning_rate_decay)

    def get_learning_rate(self, step):
        return self.schedule(self.lr, step)

    def init_state(self, params):
        return {"m": _tmap(jnp.zeros_like, params),
                "v": _tmap(jnp.zeros_like, params)}

    def update(self, step, grads, params, state):
        lr = self.schedule(self.lr, step)
        t = step + 1
        m = _tmap(lambda m, g: self.beta1 * m + (1 - self.beta1) * g,
                  state["m"], grads)
        v = _tmap(lambda v, g: self.beta2 * v + (1 - self.beta2) * g * g,
                  state["v"], grads)
        bc1 = 1 - self.beta1 ** t
        bc2 = 1 - self.beta2 ** t
        new_params = _tmap(
            lambda p, m_, v_: p - lr * (m_ / bc1) / (jnp.sqrt(v_ / bc2) + self.eps),
            params, m, v)
        return new_params, {"m": m, "v": v}


ParallelAdam = Adam


class AdamWeightDecay(OptimMethod):
    """Decoupled weight decay + linear warmup/decay — reference
    ``optim/AdamWeightDecay.scala`` (the BERT fine-tune method)."""

    def __init__(self, learning_rate: float = 1e-3, warmup_portion: float = -1.0,
                 total: int = -1, schedule: str = "linear", beta1: float = 0.9,
                 beta2: float = 0.999, epsilon: float = 1e-6,
                 weight_decay: float = 0.01):
        self.lr = learning_rate
        self.warmup_portion = warmup_portion
        self.total = total
        self.beta1, self.beta2, self.eps = beta1, beta2, epsilon
        self.weight_decay = weight_decay

    def get_learning_rate(self, step):
        if self.total <= 0:
            return self.lr
        progress = step / self.total
        warm = max(self.warmup_portion, 0.0)
        warm_lr = self.lr * progress / warm if warm > 0 else self.lr
        decay_lr = self.lr * (1.0 - progress)
        return jnp.where(progress < warm, warm_lr, decay_lr)

    def init_state(self, params):
        return {"m": _tmap(jnp.zeros_like, params),
                "v": _tmap(jnp.zeros_like, params)}

    def update(self, step, grads, params, state):
        lr = self.get_learning_rate(step)
        m = _tmap(lambda m, g: self.beta1 * m + (1 - self.beta1) * g,
                  state["m"], grads)
        v = _tmap(lambda v, g: self.beta2 * v + (1 - self.beta2) * g * g,
                  state["v"], grads)
        new_params = _tmap(
            lambda p, m_, v_: p - lr * (m_ / (jnp.sqrt(v_) + self.eps)
                                        + self.weight_decay * p),
            params, m, v)
        return new_params, {"m": m, "v": v}


class Adagrad(OptimMethod):
    """Reference ``optim/Adagrad.scala``."""

    def __init__(self, learning_rate: float = 1e-2,
                 learning_rate_decay: float = 0.0, weight_decay: float = 0.0):
        self.lr = learning_rate
        self.decay = learning_rate_decay
        self.weight_decay = weight_decay

    def init_state(self, params):
        return {"accum": _tmap(jnp.zeros_like, params)}

    def update(self, step, grads, params, state):
        lr = self.lr / (1.0 + step * self.decay)
        if self.weight_decay > 0:
            grads = _tmap(lambda g, p: g + self.weight_decay * p, grads, params)
        accum = _tmap(lambda a, g: a + g * g, state["accum"], grads)
        new_params = _tmap(
            lambda p, g, a: p - lr * g / (jnp.sqrt(a) + 1e-10), params, grads,
            accum)
        return new_params, {"accum": accum}


class RMSprop(OptimMethod):
    """Reference ``optim/RMSprop.scala``."""

    def __init__(self, learning_rate: float = 1e-2,
                 learning_rate_decay: float = 0.0, decay_rate: float = 0.99,
                 epsilon: float = 1e-8):
        self.lr = learning_rate
        self.decay = learning_rate_decay
        self.rho = decay_rate
        self.eps = epsilon

    def init_state(self, params):
        return {"rms": _tmap(jnp.zeros_like, params)}

    def update(self, step, grads, params, state):
        lr = self.lr / (1.0 + step * self.decay)
        rms = _tmap(lambda r, g: self.rho * r + (1 - self.rho) * g * g,
                    state["rms"], grads)
        new_params = _tmap(
            lambda p, g, r: p - lr * g / (jnp.sqrt(r) + self.eps), params,
            grads, rms)
        return new_params, {"rms": rms}


class Adadelta(OptimMethod):
    """Reference ``optim/Adadelta.scala`` (accumulated-delta scaling; no
    global learning rate in the classic formulation — ``learning_rate``
    multiplies the final step as in the reference)."""

    def __init__(self, learning_rate: float = 1.0, decay_rate: float = 0.9,
                 epsilon: float = 1e-10):
        self.lr = learning_rate
        self.rho = decay_rate
        self.eps = epsilon

    def init_state(self, params):
        return {"accum": _tmap(jnp.zeros_like, params),
                "delta": _tmap(jnp.zeros_like, params)}

    def update(self, step, grads, params, state):
        rho, eps = self.rho, self.eps
        accum = _tmap(lambda a, g: rho * a + (1 - rho) * g * g,
                      state["accum"], grads)
        upd = _tmap(
            lambda g, d, a: g * jnp.sqrt(d + eps) / jnp.sqrt(a + eps),
            grads, state["delta"], accum)
        delta = _tmap(lambda d, u: rho * d + (1 - rho) * u * u,
                      state["delta"], upd)
        new_params = _tmap(lambda p, u: p - self.lr * u, params, upd)
        return new_params, {"accum": accum, "delta": delta}


class Adamax(OptimMethod):
    """Reference ``optim/Adamax.scala`` (Adam with an infinity-norm second
    moment)."""

    def __init__(self, learning_rate: float = 2e-3, beta1: float = 0.9,
                 beta2: float = 0.999, epsilon: float = 1e-38):
        self.lr = learning_rate
        self.b1 = beta1
        self.b2 = beta2
        self.eps = epsilon

    def init_state(self, params):
        return {"m": _tmap(jnp.zeros_like, params),
                "u": _tmap(jnp.zeros_like, params)}

    def update(self, step, grads, params, state):
        t = step + 1
        m = _tmap(lambda m, g: self.b1 * m + (1 - self.b1) * g,
                  state["m"], grads)
        u = _tmap(lambda u, g: jnp.maximum(self.b2 * u, jnp.abs(g) + self.eps),
                  state["u"], grads)
        lr_t = self.lr / (1.0 - self.b1 ** t)
        new_params = _tmap(lambda p, m, u: p - lr_t * m / u, params, m, u)
        return new_params, {"m": m, "u": u}


class Ftrl(OptimMethod):
    """Reference ``optim/Ftrl.scala`` (recsys sparse-ish method)."""

    def __init__(self, learning_rate: float = 1e-3,
                 learning_rate_power: float = -0.5,
                 initial_accumulator_value: float = 0.1,
                 l1_regularization_strength: float = 0.0,
                 l2_regularization_strength: float = 0.0):
        self.lr = learning_rate
        self.lr_power = learning_rate_power
        self.init_accum = initial_accumulator_value
        self.l1 = l1_regularization_strength
        self.l2 = l2_regularization_strength

    def init_state(self, params):
        return {"accum": _tmap(lambda p: jnp.full_like(p, self.init_accum), params),
                "linear": _tmap(jnp.zeros_like, params)}

    def update(self, step, grads, params, state):
        def upd(p, g, n, z):
            new_n = n + g * g
            sigma = (new_n ** -self.lr_power - n ** -self.lr_power) / self.lr
            new_z = z + g - sigma * p
            new_p = jnp.where(
                jnp.abs(new_z) > self.l1,
                -(new_z - jnp.sign(new_z) * self.l1)
                / (new_n ** -self.lr_power / self.lr + 2 * self.l2),
                0.0)
            return new_p, new_n, new_z

        flat = _tmap(upd, params, grads, state["accum"], state["linear"])
        leaves, treedef = jax.tree_util.tree_flatten(
            flat, is_leaf=lambda x: isinstance(x, tuple))
        new_p = treedef.unflatten([l[0] for l in leaves])
        accum = treedef.unflatten([l[1] for l in leaves])
        linear = treedef.unflatten([l[2] for l in leaves])
        return new_p, {"accum": accum, "linear": linear}


class LarsSGD(OptimMethod):
    """Layer-wise adaptive rate scaling — reference ``optim/LarsSGD.scala``.
    Needs per-layer norms so it runs on the replicated (non-ZeRO) path."""

    elementwise = False

    def __init__(self, learning_rate: float = 1e-1, momentum: float = 0.9,
                 weight_decay: float = 5e-4, trust_coefficient: float = 1e-3,
                 learning_rate_schedule: Optional[LearningRateSchedule] = None):
        self.lr = learning_rate
        self.momentum = momentum
        self.weight_decay = weight_decay
        self.trust = trust_coefficient
        self.schedule = learning_rate_schedule or Default(0.0)

    def get_learning_rate(self, step):
        return self.schedule(self.lr, step)

    def init_state(self, params):
        return {"velocity": _tmap(jnp.zeros_like, params)}

    def update(self, step, grads, params, state):
        lr = self.schedule(self.lr, step)

        def upd(p, g, v):
            p_norm = jnp.linalg.norm(p.ravel())
            g_norm = jnp.linalg.norm(g.ravel())
            local_lr = jnp.where(
                (p_norm > 0) & (g_norm > 0),
                self.trust * p_norm / (g_norm + self.weight_decay * p_norm + 1e-12),
                1.0)
            new_v = self.momentum * v + lr * local_lr * (
                g + self.weight_decay * p)
            return p - new_v, new_v

        flat = _tmap(upd, params, grads, state["velocity"])
        leaves, treedef = jax.tree_util.tree_flatten(
            flat, is_leaf=lambda x: isinstance(x, tuple))
        new_p = treedef.unflatten([l[0] for l in leaves])
        vel = treedef.unflatten([l[1] for l in leaves])
        return new_p, {"velocity": vel}


class LBFGS(OptimMethod):
    """Limited-memory BFGS — reference ``optim/LBFGS.scala``.

    Pure-functional two-loop recursion with a fixed-size (s, y) history kept
    in the optimizer state as stacked arrays, so one ``update`` per gradient
    (no inner line search — fixed ``learning_rate`` step; the reference's
    line-search variant needs multiple evals per step, which doesn't fit a
    one-grad-per-iteration jitted train loop.  Documented divergence).

    Needs whole-vector dot products, so it requires the replicated (non-ZeRO)
    path: ``elementwise = False``."""

    elementwise = False

    def __init__(self, learning_rate: float = 1.0, history_size: int = 10,
                 eps: float = 1e-10):
        self.lr = learning_rate
        self.m = history_size
        self.eps = eps

    def _dot(self, a, b):
        leaves_a = jax.tree_util.tree_leaves(a)
        leaves_b = jax.tree_util.tree_leaves(b)
        return sum(jnp.vdot(x, y) for x, y in zip(leaves_a, leaves_b))

    def init_state(self, params):
        def hist(p):
            return jnp.zeros((self.m,) + p.shape, p.dtype)

        return {
            "s": _tmap(hist, params), "y": _tmap(hist, params),
            "rho": jnp.zeros((self.m,)),
            "prev_params": _tmap(jnp.zeros_like, params),
            "prev_grads": _tmap(jnp.zeros_like, params),
            "count": jnp.zeros((), jnp.int32),
        }

    def update(self, step, grads, params, state):
        count = state["count"]

        def roll_in(h, new):
            return jnp.concatenate([h[1:], new[None]], axis=0)

        s_new = _tmap(lambda p, q: p - q, params, state["prev_params"])
        y_new = _tmap(lambda g, h: g - h, grads, state["prev_grads"])
        ys = self._dot(y_new, s_new)
        valid = (count > 0) & (ys > self.eps)

        s_hist = _tmap(
            lambda h, n: jnp.where(valid, roll_in(h, n), h), state["s"], s_new)
        y_hist = _tmap(
            lambda h, n: jnp.where(valid, roll_in(h, n), h), state["y"], y_new)
        rho = jnp.where(
            valid,
            jnp.concatenate([state["rho"][1:],
                             (1.0 / jnp.maximum(ys, self.eps))[None]]),
            state["rho"])

        # two-loop recursion; rho==0 entries are no-ops so masking is implicit
        q = grads
        alphas = []
        for i in range(self.m - 1, -1, -1):
            s_i = _tmap(lambda h: h[i], s_hist)
            y_i = _tmap(lambda h: h[i], y_hist)
            a_i = rho[i] * self._dot(s_i, q)
            q = _tmap(lambda qq, yy: qq - a_i * yy, q, y_i)
            alphas.append((i, a_i))
        # initial Hessian scale gamma = s·y / y·y of the newest valid pair
        y_last = _tmap(lambda h: h[-1], y_hist)
        s_last = _tmap(lambda h: h[-1], s_hist)
        yy = self._dot(y_last, y_last)
        gamma = jnp.where(yy > self.eps,
                          self._dot(s_last, y_last) / jnp.maximum(yy, self.eps),
                          1.0)
        q = _tmap(lambda qq: gamma * qq, q)
        for i, a_i in reversed(alphas):
            s_i = _tmap(lambda h: h[i], s_hist)
            y_i = _tmap(lambda h: h[i], y_hist)
            b_i = rho[i] * self._dot(y_i, q)
            q = _tmap(lambda qq, ss: qq + (a_i - b_i) * ss, q, s_i)

        new_params = _tmap(lambda p, d: p - self.lr * d, params, q)
        new_state = {
            "s": s_hist, "y": y_hist, "rho": rho,
            "prev_params": params, "prev_grads": grads,
            "count": count + 1,
        }
        return new_params, new_state
