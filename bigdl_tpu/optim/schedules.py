"""Learning-rate schedules.

Reference analog (unverified — mount empty): inner classes of
``dllib/optim/SGD.scala`` — ``Default``, ``Step``, ``MultiStep``,
``Exponential``, ``Poly``, ``Plateau``, ``Warmup``, ``SequentialSchedule``,
``EpochStep``, ``EpochDecay``, ``EpochSchedule``, ``NaturalExp``.  Functional here: ``schedule(step) -> lr
multiplier-resolved absolute lr``, traceable inside jit (pure jnp math on the
step counter, no data-dependent python control flow).
"""

from typing import List, Optional, Sequence, Tuple

import jax.numpy as jnp


class LearningRateSchedule:
    def __call__(self, lr: float, step):
        raise NotImplementedError


class Default(LearningRateSchedule):
    """lr / (1 + step*decay) — SGD.Default in the reference."""

    def __init__(self, learning_rate_decay: float = 0.0):
        self.decay = learning_rate_decay

    def __call__(self, lr, step):
        return lr / (1.0 + step * self.decay)


class Step(LearningRateSchedule):
    """lr * gamma^(floor(step/step_size)) — SGD.Step."""

    def __init__(self, step_size: int, gamma: float = 0.1):
        self.step_size = step_size
        self.gamma = gamma

    def __call__(self, lr, step):
        return lr * self.gamma ** jnp.floor(step / self.step_size)


class MultiStep(LearningRateSchedule):
    """lr * gamma^(#milestones passed) — SGD.MultiStep."""

    def __init__(self, step_sizes: Sequence[int], gamma: float = 0.1):
        self.step_sizes = jnp.asarray(step_sizes)
        self.gamma = gamma

    def __call__(self, lr, step):
        passed = jnp.sum(step >= self.step_sizes)
        return lr * self.gamma ** passed


class Exponential(LearningRateSchedule):
    """SGD.Exponential: lr * decay_rate^(step/decay_step), optionally staircase."""

    def __init__(self, decay_step: int, decay_rate: float, stair_case: bool = False):
        self.decay_step = decay_step
        self.decay_rate = decay_rate
        self.stair_case = stair_case

    def __call__(self, lr, step):
        p = step / self.decay_step
        if self.stair_case:
            p = jnp.floor(p)
        return lr * self.decay_rate ** p


class NaturalExp(LearningRateSchedule):
    def __init__(self, decay_step: int, gamma: float):
        self.decay_step = decay_step
        self.gamma = gamma

    def __call__(self, lr, step):
        return lr * jnp.exp(-self.gamma * jnp.floor(step / self.decay_step))


class Poly(LearningRateSchedule):
    """lr * (1 - step/max_iter)^power — SGD.Poly (the reference ResNet/
    ImageNet schedule)."""

    def __init__(self, power: float, max_iteration: int):
        self.power = power
        self.max_iteration = max_iteration

    def __call__(self, lr, step):
        frac = jnp.clip(step / self.max_iteration, 0.0, 1.0)
        return lr * (1.0 - frac) ** self.power


class EpochStep(LearningRateSchedule):
    """lr * gamma^(floor(epoch / step_size_epochs)) — reference
    ``SGD.EpochStep``.  The reference reads the epoch from driver state;
    under jit the epoch is derived as ``step // steps_per_epoch`` (pass
    the dataset's batches-per-epoch)."""

    def __init__(self, step_size_epochs: int, gamma: float,
                 steps_per_epoch: int):
        self.step_size = step_size_epochs
        self.gamma = gamma
        self.steps_per_epoch = steps_per_epoch

    def __call__(self, lr, step):
        epoch = jnp.floor(step / self.steps_per_epoch)
        return lr * self.gamma ** jnp.floor(epoch / self.step_size)


class EpochDecay(LearningRateSchedule):
    """lr * 0.1^(decay_fn(epoch)) — reference ``SGD.EpochDecay`` (the
    function-of-epoch decay).  ``decay_fn`` must be jnp-traceable (it runs
    inside the jitted step on a traced epoch index)."""

    def __init__(self, decay_fn, steps_per_epoch: int):
        self.decay_fn = decay_fn
        self.steps_per_epoch = steps_per_epoch

    def __call__(self, lr, step):
        epoch = jnp.floor(step / self.steps_per_epoch)
        return lr * 0.1 ** self.decay_fn(epoch)


class EpochSchedule(LearningRateSchedule):
    """Piecewise-constant lr by epoch regimes — reference
    ``SGD.EpochSchedule(regimes)`` with ``Regime(startEpoch, endEpoch,
    lr)``; epochs are 1-based and inclusive like the reference.  Past the
    last regime — or in a gap BETWEEN regimes — the most recently matched
    regime's rate persists (the reference mutates a persistent config in
    order, so the previous regime's rate sticks)."""

    def __init__(self, regimes: Sequence[Tuple[int, int, float]],
                 steps_per_epoch: int):
        if not regimes:
            raise ValueError("EpochSchedule needs at least one regime")
        # carry-forward iteration needs start-epoch order (the reference
        # accepts any order; sorting preserves its semantics)
        self.regimes = tuple(sorted(regimes, key=lambda r: r[0]))
        self.steps_per_epoch = steps_per_epoch

    def __call__(self, lr, step):
        epoch = jnp.floor(step / self.steps_per_epoch) + 1
        # carry-forward semantics: each regime claims epochs from its start
        # onward until a later regime's start overrides it
        out = lr
        for start, _end, value in self.regimes:
            out = jnp.where(epoch >= start, value, out)
        return out


class Cosine(LearningRateSchedule):
    """Cosine decay to ``alpha * lr`` over ``decay_steps`` (the standard
    TPU large-batch recipe tail; pair with ``Warmup`` in a
    ``SequentialSchedule``).  Past ``decay_steps`` the floor persists."""

    def __init__(self, decay_steps: int, alpha: float = 0.0):
        if decay_steps <= 0:
            raise ValueError("decay_steps must be positive")
        self.decay_steps = decay_steps
        self.alpha = alpha

    def __call__(self, lr, step):
        frac = jnp.clip(step / self.decay_steps, 0.0, 1.0)
        cos = 0.5 * (1.0 + jnp.cos(jnp.pi * frac))
        return lr * ((1 - self.alpha) * cos + self.alpha)


class Warmup(LearningRateSchedule):
    """Linear ramp by delta per step — SGD.Warmup (pair inside
    SequentialSchedule like the reference's large-batch ImageNet recipe)."""

    def __init__(self, delta: float):
        self.delta = delta

    def __call__(self, lr, step):
        return lr + self.delta * step


class SequentialSchedule(LearningRateSchedule):
    """Chain schedules, each active for ``iterations`` steps — SGD.
    SequentialSchedule."""

    def __init__(self):
        self.schedules: List[Tuple[LearningRateSchedule, int]] = []

    def add(self, schedule: LearningRateSchedule, iterations: int):
        self.schedules.append((schedule, iterations))
        return self

    def __call__(self, lr, step):
        out = lr
        offset = 0
        # resolved as nested where's — fine for a handful of phases
        result = None
        for schedule, iters in self.schedules:
            local = jnp.clip(step - offset, 0, iters)
            val = schedule(lr, local)
            active = step >= offset
            result = val if result is None else jnp.where(active, val, result)
            offset += iters
        return result if result is not None else out


class Plateau(LearningRateSchedule):
    """Reduce-on-plateau — reference ``SGD.Plateau(monitor, factor,
    patience, mode, epsilon, cooldown, minLr)``.

    Score-driven: the Optimizer feeds validation results to ``on_score``
    after every validation trigger.  When the monitored score stops
    improving for ``patience`` validations, the factor shrinks and the
    driver recompiles the train step with the new effective LR (drops are
    rare, so the recompile cost is negligible over a run)."""

    def __init__(self, factor: float = 0.1, patience: int = 10,
                 mode: str = "max", epsilon: float = 1e-4,
                 cooldown: int = 0, min_lr: float = 0.0,
                 monitor: Optional[str] = None):
        if mode not in ("min", "max"):
            raise ValueError("mode: min | max")
        self.factor = factor
        self.patience = patience
        self.mode = mode
        self.epsilon = epsilon
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.monitor = monitor  # validation-method name; None = first result
        self.current_factor = 1.0
        self._best = None
        self._bad = 0
        self._cooling = 0
        self._last_base_lr: Optional[float] = None

    # -- checkpointable state (driver saves/restores across resume) ---------
    def state_dict(self) -> dict:
        return {"current_factor": self.current_factor, "best": self._best,
                "bad": self._bad, "cooling": self._cooling}

    def load_state_dict(self, d: dict) -> None:
        self.current_factor = float(d["current_factor"])
        self._best = d["best"]
        self._bad = int(d["bad"])
        self._cooling = int(d["cooling"])

    def on_score(self, score: float) -> bool:
        """Record one validation score; returns True when the LR factor
        changed (caller must recompile)."""
        improved = (self._best is None
                    or (self.mode == "max" and score > self._best + self.epsilon)
                    or (self.mode == "min" and score < self._best - self.epsilon))
        if improved:
            self._best = score
            self._bad = 0
            return False
        if self._cooling > 0:
            self._cooling -= 1
            return False
        self._bad += 1
        # keras ReduceLROnPlateau semantics (the reference SGD.Plateau
        # follows them): reduce when wait >= patience, i.e. on the
        # patience-th consecutive non-improving validation
        if self._bad >= self.patience:
            self._bad = 0
            self._cooling = self.cooldown
            if (self._last_base_lr is not None
                    and self._last_base_lr * self.current_factor
                    <= self.min_lr):
                return False  # already floored: no change, no recompile
            self.current_factor = self.current_factor * self.factor
            return True
        return False

    def __call__(self, lr, step):
        # current_factor is a host float baked at trace time; the Optimizer
        # rebuilds the compiled step whenever on_score changes it
        self._last_base_lr = float(lr)
        return max(lr * self.current_factor, self.min_lr)
