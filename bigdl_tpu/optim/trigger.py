"""Triggers — when to stop / validate / checkpoint.

Reference analog (unverified — mount empty): ``dllib/optim/Trigger.scala`` —
``everyEpoch``, ``severalIteration``, ``maxEpoch``, ``maxIteration``,
``maxScore``, ``minLoss``, ``and``/``or``.  Evaluated host-side on the driver
state dict (epoch, iteration ["neval"], loss, score, epoch_finished).
"""

from typing import Callable, Dict


class Trigger:
    def __init__(self, fn: Callable[[Dict], bool], desc: str = "trigger"):
        self.fn = fn
        self.desc = desc

    def __call__(self, state: Dict) -> bool:
        return bool(self.fn(state))

    def __repr__(self):
        return f"Trigger({self.desc})"

    # -- factories (reference names, snake_case) ---------------------------
    @staticmethod
    def every_epoch() -> "Trigger":
        """Fires at each epoch boundary (reference everyEpoch)."""
        return Trigger(lambda s: s.get("epoch_finished", False), "every_epoch")

    @staticmethod
    def several_iteration(n: int) -> "Trigger":
        return Trigger(lambda s: s["iteration"] > 0 and s["iteration"] % n == 0,
                       f"several_iteration({n})")

    @staticmethod
    def max_epoch(n: int) -> "Trigger":
        """True once epoch count exceeds n (epochs are 1-based like the
        reference)."""
        return Trigger(lambda s: s["epoch"] > n, f"max_epoch({n})")

    @staticmethod
    def max_iteration(n: int) -> "Trigger":
        return Trigger(lambda s: s["iteration"] >= n, f"max_iteration({n})")

    @staticmethod
    def min_loss(v: float) -> "Trigger":
        return Trigger(lambda s: s.get("loss", float("inf")) < v, f"min_loss({v})")

    @staticmethod
    def max_score(v: float) -> "Trigger":
        return Trigger(lambda s: s.get("score", float("-inf")) > v,
                       f"max_score({v})")

    @staticmethod
    def and_(*triggers: "Trigger") -> "Trigger":
        return Trigger(lambda s: all(t(s) for t in triggers), "and")

    @staticmethod
    def or_(*triggers: "Trigger") -> "Trigger":
        return Trigger(lambda s: any(t(s) for t in triggers), "or")
