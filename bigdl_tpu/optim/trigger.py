"""Triggers — when to stop / validate / checkpoint.

Reference analog (unverified — mount empty): ``dllib/optim/Trigger.scala`` —
``everyEpoch``, ``severalIteration``, ``maxEpoch``, ``maxIteration``,
``maxScore``, ``minLoss``, ``and``/``or``.  Evaluated host-side on the driver
state dict (epoch, iteration ["neval"], loss, score, epoch_finished).

Step bundling (docs/performance.md): with ``steps_per_call > 1`` the driver
only regains control at bundle boundaries, so triggers are EVALUATED at
bundle edges.  Iteration-structured triggers expose a ``boundary`` hint —
``boundary(iteration) -> steps until the next firing edge (or None)`` — and
the driver SHORTENS a bundle so that edge lands exactly on a bundle
boundary: ``several_iteration(4)`` still checkpoints at iteration 4 under
``steps_per_call=8``.  Triggers without iteration structure (loss/score/
plateau) quantize to bundle granularity.
"""

from typing import Callable, Dict, Optional


class Trigger:
    def __init__(self, fn: Callable[[Dict], bool], desc: str = "trigger",
                 boundary: Optional[Callable[[int], Optional[int]]] = None):
        self.fn = fn
        self.desc = desc
        self.boundary = boundary

    def __call__(self, state: Dict) -> bool:
        return bool(self.fn(state))

    def __repr__(self):
        return f"Trigger({self.desc})"

    # -- factories (reference names, snake_case) ---------------------------
    @staticmethod
    def every_epoch() -> "Trigger":
        """Fires at each epoch boundary (reference everyEpoch)."""
        return Trigger(lambda s: s.get("epoch_finished", False), "every_epoch")

    @staticmethod
    def several_iteration(n: int) -> "Trigger":
        return Trigger(lambda s: s["iteration"] > 0 and s["iteration"] % n == 0,
                       f"several_iteration({n})",
                       boundary=lambda it: n - it % n)

    @staticmethod
    def max_epoch(n: int) -> "Trigger":
        """True once epoch count exceeds n (epochs are 1-based like the
        reference)."""
        return Trigger(lambda s: s["epoch"] > n, f"max_epoch({n})")

    @staticmethod
    def max_iteration(n: int) -> "Trigger":
        return Trigger(lambda s: s["iteration"] >= n, f"max_iteration({n})",
                       boundary=lambda it: n - it if it < n else None)

    @staticmethod
    def min_loss(v: float) -> "Trigger":
        return Trigger(lambda s: s.get("loss", float("inf")) < v, f"min_loss({v})")

    @staticmethod
    def max_score(v: float) -> "Trigger":
        return Trigger(lambda s: s.get("score", float("-inf")) > v,
                       f"max_score({v})")

    @staticmethod
    def plateau(monitor: str = "score", patience: int = 3,
                min_delta: float = 0.0) -> "Trigger":
        """Early stopping: fire when ``monitor`` ("score": higher-better
        validation score, observed once per VALIDATION EVENT; "loss":
        lower-better training loss, observed once per EPOCH) has not
        improved by ``min_delta`` for ``patience`` consecutive
        observations.  The keras-EarlyStopping analog as an end-when
        trigger (stateful: one instance tracks one run).  end_when runs
        every iteration, so observations are gated on the event counter —
        re-seeing the same score between validations does not burn
        patience."""
        if monitor not in ("score", "loss"):
            raise ValueError(
                f"plateau monitor {monitor!r}: 'score' (validation, "
                "higher-better) or 'loss' (training, lower-better)")
        higher_better = monitor == "score"
        event_key = "n_validations" if monitor == "score" else "epoch"
        best = [None]
        stale = [0]
        last_event = [None]

        def fn(s):
            event = s.get(event_key)
            # strictly monotonic: a failure-retry resume rolls the driver
            # state back and REPLAYS events — re-observing them would burn
            # patience twice and fire early.  Skipping replays only delays
            # the stop (conservative).
            if event is None or (last_event[0] is not None
                                 and event <= last_event[0]):
                return stale[0] >= patience  # no NEW observation
            v = s.get(monitor)
            try:
                v = float(v)
            except (TypeError, ValueError):
                return False
            if v != v or v in (float("inf"), float("-inf")):
                return False
            last_event[0] = event
            improved = (best[0] is None
                        or (v > best[0] + min_delta if higher_better
                            else v < best[0] - min_delta))
            if improved:
                best[0] = v
                stale[0] = 0
            else:
                stale[0] += 1
            return stale[0] >= patience

        return Trigger(fn, f"plateau({monitor}, patience={patience})")

    @staticmethod
    def _child_boundary(triggers):
        """Earliest iteration edge of any child — shortening a bundle more
        than strictly needed is always safe (it only adds an extra host
        visit), missing an edge is not."""
        def boundary(it):
            edges = [b(it) for b in
                     (getattr(t, "boundary", None) for t in triggers)
                     if b is not None]
            edges = [e for e in edges if e is not None and e > 0]
            return min(edges) if edges else None

        return boundary

    @staticmethod
    def and_(*triggers: "Trigger") -> "Trigger":
        return Trigger(lambda s: all(t(s) for t in triggers), "and",
                       boundary=Trigger._child_boundary(triggers))

    @staticmethod
    def or_(*triggers: "Trigger") -> "Trigger":
        return Trigger(lambda s: any(t(s) for t in triggers), "or",
                       boundary=Trigger._child_boundary(triggers))
