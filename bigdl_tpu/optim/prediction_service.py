"""Thread-safe concurrent prediction — reference ``optim/PredictionService``.

Reference analog (unverified — mount empty): ``optim/PredictionService.scala``
holds ``numThreads`` cloned model instances in a blocking queue; each
``predict`` call takes one, runs forward, and returns it, so concurrent
callers never share mutable layer state.

TPU-native re-design: the compiled program is pure, so there is nothing to
clone — one jitted forward is safe under any concurrency.  What survives is
the *capacity discipline*: a semaphore of ``n_replicas`` permits bounds
in-flight predicts (on-device queueing stays shallow, latency stays
predictable), and per-call errors are caught and returned like the
reference's ``Result`` wrapper instead of tearing down the service.
"""

import threading
from typing import Any, Dict, Optional

import numpy as np

from bigdl_tpu.serving.inference_model import InferenceModel


class PredictionService:
    def __init__(self, model=None, variables: Optional[Dict[str, Any]] = None,
                 n_replicas: int = 2, predict_fn=None):
        self._im = InferenceModel(model, variables, predict_fn=predict_fn)
        if n_replicas < 1:
            raise ValueError("n_replicas must be >= 1")
        self._sem = threading.Semaphore(n_replicas)

    def predict(self, x) -> np.ndarray:
        """Blocking predict; safe from any number of threads."""
        with self._sem:
            return self._im.predict(np.asarray(x))

    def try_predict(self, x):
        """Reference ``PredictionService.predict`` error contract: returns
        (result, None) or (None, exception) instead of raising."""
        try:
            return self.predict(x), None
        except Exception as e:  # noqa: BLE001 — service must stay up
            return None, e
