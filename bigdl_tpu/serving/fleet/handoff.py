"""Serialized KV-page transfer — the prefill/decode split's wire format.

A prefill worker runs the whole chunked prefill (selecting the first
token during the final chunk, exactly as a local request would), then
ships the finished pages to a decode worker as ``pack_handoff`` bytes:
a fixed magic + length-prefixed JSON header (tokens, first token and its
log-prob, sampling params, seed, array shape, ``kv_dtype``) followed by
the raw page images of K then V — float32, or int8 followed by the
per-(layer, page) float32 scale tables (K scales then V scales), ~4x
fewer wire bytes per page.  A header without ``kv_dtype`` is a blob
from before the field existed and is read as float32; an unrecognized
``kv_dtype`` is rejected BY NAME rather than misread as f32.

The format is deliberately *exact*: ``tobytes()``/``frombuffer`` round-
trips every float32 bit, and the first token's log-prob travels as a
Python float (binary64 superset of the engine's float32, and JSON's
shortest-repr round-trips binary64 exactly), so importing a handoff on
the decode worker reproduces byte-for-byte the state the prefill worker
would have continued from — the byte-identical-to-``static_generate``
parity invariant survives the process boundary.  tests/test_fleet.py
proves pack→unpack is an exact round-trip and that a cross-engine
handoff decode matches ``static_generate``.

K/V arrays are shaped ``(layers, pages, kv_heads, page_size, head_dim)``
— the engine's page-pool layout with the page axis narrowed to the pages
the prompt covers.  Positions in the last page at or beyond the prompt
length carry whatever the prefill padding wrote; the decode engine
overwrites each such position before ever attending to it (the same
argument that makes slot reuse aliasing-free), so they need no masking
here.
"""

import json
from typing import Any, Dict, Optional

import numpy as np

__all__ = ["pack_handoff", "unpack_handoff", "HANDOFF_MAGIC",
           "HandoffError", "MAX_HANDOFF_BYTES"]

HANDOFF_MAGIC = b"BDLFKV1\n"

# header fields every handoff carries; anything else JSON-serializable
# rides along untouched (request_id, deadline, tenant...)
_REQUIRED = ("tokens", "first_token", "first_logp")

# hard ceiling on an accepted blob: a misbehaving (or chaos-injected)
# prefill worker must not be able to make a decode worker materialize an
# unbounded numpy array.  256 MiB covers every geometry this repo ships
# (the bench fleet's largest handoff is < 1 MiB) with 2+ orders of
# margin; callers with bigger pools pass max_bytes explicitly.
MAX_HANDOFF_BYTES = 256 * 1024 * 1024

# the JSON header is small (tokens + sampling meta); a multi-megabyte
# header length is corruption, not a big request
_MAX_HEADER_BYTES = 16 * 1024 * 1024


class HandoffError(ValueError):
    """A handoff blob failed validation — corrupt magic, truncated or
    lying header, payload shorter than the header promises, or a
    page/byte count over the caller's bound.  Subclasses ValueError so
    pre-existing ``pytest.raises(ValueError, ...)`` specs (and callers
    catching ValueError) keep working; raised *before* any page is
    allocated on the importing engine, so a rejected blob never leaves
    partially-imported state behind."""


def pack_handoff(h: Dict[str, Any]) -> bytes:
    """Serialize a handoff dict (as built by the engine's ``export_kv``
    path) to transfer bytes.  ``h["k"]``/``h["v"]`` are the page images
    in the engine's stored page dtype — float32, or int8 with the
    per-(layer, page) float32 ``k_scales``/``v_scales`` riding behind
    the V payload (an int8 blob is ~4x smaller on the wire); every
    other key must be JSON-serializable."""
    kv_dtype = str(h.get("kv_dtype", "float32"))
    if kv_dtype not in ("float32", "int8"):
        raise ValueError(f"unsupported handoff kv_dtype {kv_dtype!r}")
    dt = np.int8 if kv_dtype == "int8" else np.float32
    k = np.ascontiguousarray(np.asarray(h["k"], dt))
    v = np.ascontiguousarray(np.asarray(h["v"], dt))
    if k.shape != v.shape or k.ndim != 5:
        raise ValueError(f"handoff K/V must share a 5-d page-pool shape, "
                         f"got k={k.shape} v={v.shape}")
    payload = [k.tobytes(), v.tobytes()]
    if kv_dtype == "int8":
        ks = np.ascontiguousarray(np.asarray(h.get("k_scales"),
                                             np.float32))
        vs = np.ascontiguousarray(np.asarray(h.get("v_scales"),
                                             np.float32))
        if ks.shape != k.shape[:2] or vs.shape != k.shape[:2]:
            raise ValueError(
                f"int8 handoff needs (layers, pages) scale tables "
                f"{k.shape[:2]}, got k_scales={ks.shape} "
                f"v_scales={vs.shape}")
        payload += [ks.tobytes(), vs.tobytes()]
    header = {key: val for key, val in h.items()
              if key not in ("k", "v", "k_scales", "v_scales")}
    for key in _REQUIRED:
        if key not in header:
            raise ValueError(f"handoff missing required field {key!r}")
    header["tokens"] = [int(t) for t in header["tokens"]]
    header["first_token"] = int(header["first_token"])
    header["first_logp"] = float(header["first_logp"])
    header["shape"] = list(k.shape)
    # "dtype" is the pre-kv_dtype name for the same field: writing both
    # keeps an int8 blob REJECTED (not silently misread as f32) by
    # decoders from before kv_dtype existed, and f32 blobs bit-identical
    # to what those decoders always produced
    header["dtype"] = kv_dtype
    header["kv_dtype"] = kv_dtype
    header["version"] = 1
    hdr = json.dumps(header, sort_keys=True).encode()
    return b"".join([HANDOFF_MAGIC, len(hdr).to_bytes(8, "big"), hdr]
                    + payload)


def unpack_handoff(data: bytes, max_bytes: int = MAX_HANDOFF_BYTES,
                   max_pages: Optional[int] = None) -> Dict[str, Any]:
    """Exact inverse of :func:`pack_handoff`, hardened against corrupt
    or adversarial blobs: every structural violation raises
    :class:`HandoffError` before any array is materialized.

    ``max_bytes`` bounds the accepted blob size; ``max_pages`` (when
    given, e.g. the importing engine's ``prefix_cache_pages``) bounds
    the page axis of the declared shape so a bad prefill worker can't
    make the decode worker allocate pages it doesn't have."""
    if len(data) > max_bytes:
        raise HandoffError(f"handoff blob of {len(data)} bytes exceeds "
                           f"the {max_bytes}-byte bound")
    if not data.startswith(HANDOFF_MAGIC):
        raise HandoffError("not a KV handoff (bad magic)")
    off = len(HANDOFF_MAGIC)
    if len(data) < off + 8:
        raise HandoffError("handoff truncated: header length missing")
    hlen = int.from_bytes(data[off:off + 8], "big")
    off += 8
    if hlen > _MAX_HEADER_BYTES or off + hlen > len(data):
        raise HandoffError(f"handoff truncated: header claims {hlen} "
                           f"bytes, blob has {len(data) - off} after it")
    try:
        header = json.loads(data[off:off + hlen].decode())
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise HandoffError(f"handoff header is not valid JSON: {e}")
    if not isinstance(header, dict):
        raise HandoffError("handoff header must be a JSON object")
    off += hlen
    if header.get("version") != 1:
        raise HandoffError(f"unsupported handoff version "
                           f"{header.get('version')!r}")
    for key in _REQUIRED:
        if key not in header:
            raise HandoffError(f"handoff missing required field {key!r}")
    if (not isinstance(header["tokens"], list)
            or not all(isinstance(t, int) for t in header["tokens"])):
        raise HandoffError("handoff tokens must be a list of ints")
    raw_shape = header.pop("shape", None)
    if (not isinstance(raw_shape, list) or len(raw_shape) != 5
            or not all(isinstance(d, int) and d >= 0 for d in raw_shape)):
        raise HandoffError(f"handoff K/V must share a 5-d page-pool "
                           f"shape, got {raw_shape!r}")
    shape = tuple(raw_shape)
    legacy_dt = header.pop("dtype", None)
    kv_dtype = header.pop("kv_dtype", legacy_dt or "float32")
    if kv_dtype not in ("float32", "int8"):
        # NAME the dtype: a future blob must be rejected loudly (HTTP
        # 400 at the serving frontend), never misread as f32 pages
        raise HandoffError(f"unsupported handoff kv_dtype {kv_dtype!r} "
                           "(this build understands float32 and int8)")
    if legacy_dt is not None and legacy_dt != kv_dtype:
        raise HandoffError(f"handoff header dtype {legacy_dt!r} "
                           f"contradicts kv_dtype {kv_dtype!r}")
    if max_pages is not None and shape[1] > max_pages:
        raise HandoffError(f"handoff declares {shape[1]} pages, over the "
                           f"importer's {max_pages}-page bound")
    dt = np.int8 if kv_dtype == "int8" else np.float32
    itemsize = dt().itemsize
    elems = int(np.prod(shape, dtype=np.int64))
    nbytes = elems * itemsize
    n_scales = shape[0] * shape[1]          # one per (layer, page)
    scale_bytes = 2 * n_scales * 4 if kv_dtype == "int8" else 0
    total = 2 * nbytes + scale_bytes
    if total > max_bytes:
        raise HandoffError(f"handoff shape {shape} implies {total} "
                           f"payload bytes, over the {max_bytes}-byte "
                           "bound")
    if len(data) != off + total:
        raise HandoffError(f"handoff payload truncated: expected "
                           f"{off + total} bytes, got {len(data)}")
    k = np.frombuffer(data, dt, count=elems, offset=off).reshape(shape)
    v = np.frombuffer(data, dt, count=elems,
                      offset=off + nbytes).reshape(shape)
    out = dict(header)
    out["tokens"] = np.asarray(header["tokens"], np.int32)
    out["kv_dtype"] = kv_dtype
    out["k"] = k
    out["v"] = v
    if kv_dtype == "int8":
        so = off + 2 * nbytes
        out["k_scales"] = np.frombuffer(
            data, np.float32, count=n_scales,
            offset=so).reshape(shape[0], shape[1])
        out["v_scales"] = np.frombuffer(
            data, np.float32, count=n_scales,
            offset=so + n_scales * 4).reshape(shape[0], shape[1])
    return out
