"""Serialized KV-page transfer — the prefill/decode split's wire format.

A prefill worker runs the whole chunked prefill (selecting the first
token during the final chunk, exactly as a local request would), then
ships the finished pages to a decode worker as ``pack_handoff`` bytes:
a fixed magic + length-prefixed JSON header (tokens, first token and its
log-prob, sampling params, seed, array shape) followed by the raw
float32 page images of K then V.

The format is deliberately *exact*: ``tobytes()``/``frombuffer`` round-
trips every float32 bit, and the first token's log-prob travels as a
Python float (binary64 superset of the engine's float32, and JSON's
shortest-repr round-trips binary64 exactly), so importing a handoff on
the decode worker reproduces byte-for-byte the state the prefill worker
would have continued from — the byte-identical-to-``static_generate``
parity invariant survives the process boundary.  tests/test_fleet.py
proves pack→unpack is an exact round-trip and that a cross-engine
handoff decode matches ``static_generate``.

K/V arrays are shaped ``(layers, pages, kv_heads, page_size, head_dim)``
— the engine's page-pool layout with the page axis narrowed to the pages
the prompt covers.  Positions in the last page at or beyond the prompt
length carry whatever the prefill padding wrote; the decode engine
overwrites each such position before ever attending to it (the same
argument that makes slot reuse aliasing-free), so they need no masking
here.
"""

import json
from typing import Any, Dict

import numpy as np

__all__ = ["pack_handoff", "unpack_handoff", "HANDOFF_MAGIC"]

HANDOFF_MAGIC = b"BDLFKV1\n"

# header fields every handoff carries; anything else JSON-serializable
# rides along untouched (request_id, deadline, tenant...)
_REQUIRED = ("tokens", "first_token", "first_logp")


def pack_handoff(h: Dict[str, Any]) -> bytes:
    """Serialize a handoff dict (as built by the engine's ``export_kv``
    path) to transfer bytes.  ``h["k"]``/``h["v"]`` are the float32 page
    images; every other key must be JSON-serializable."""
    k = np.ascontiguousarray(np.asarray(h["k"], np.float32))
    v = np.ascontiguousarray(np.asarray(h["v"], np.float32))
    if k.shape != v.shape or k.ndim != 5:
        raise ValueError(f"handoff K/V must share a 5-d page-pool shape, "
                         f"got k={k.shape} v={v.shape}")
    header = {key: val for key, val in h.items() if key not in ("k", "v")}
    for key in _REQUIRED:
        if key not in header:
            raise ValueError(f"handoff missing required field {key!r}")
    header["tokens"] = [int(t) for t in header["tokens"]]
    header["first_token"] = int(header["first_token"])
    header["first_logp"] = float(header["first_logp"])
    header["shape"] = list(k.shape)
    header["dtype"] = "float32"
    header["version"] = 1
    hdr = json.dumps(header, sort_keys=True).encode()
    return b"".join([HANDOFF_MAGIC, len(hdr).to_bytes(8, "big"), hdr,
                     k.tobytes(), v.tobytes()])


def unpack_handoff(data: bytes) -> Dict[str, Any]:
    """Exact inverse of :func:`pack_handoff`."""
    if not data.startswith(HANDOFF_MAGIC):
        raise ValueError("not a KV handoff (bad magic)")
    off = len(HANDOFF_MAGIC)
    hlen = int.from_bytes(data[off:off + 8], "big")
    off += 8
    header = json.loads(data[off:off + hlen].decode())
    off += hlen
    if header.get("version") != 1:
        raise ValueError(f"unsupported handoff version "
                         f"{header.get('version')!r}")
    shape = tuple(header.pop("shape"))
    if header.pop("dtype") != "float32":
        raise ValueError("handoff dtype must be float32")
    nbytes = int(np.prod(shape)) * 4
    if len(data) != off + 2 * nbytes:
        raise ValueError(f"handoff payload truncated: expected "
                         f"{off + 2 * nbytes} bytes, got {len(data)}")
    k = np.frombuffer(data, np.float32, count=nbytes // 4,
                      offset=off).reshape(shape)
    v = np.frombuffer(data, np.float32, count=nbytes // 4,
                      offset=off + nbytes).reshape(shape)
    out = dict(header)
    out["tokens"] = np.asarray(header["tokens"], np.int32)
    out["k"] = k
    out["v"] = v
    return out
