"""KV-aware request placement over worker decode-pressure signals.

Round-robin is the right default for stateless predicts, but a generate
request pins a decode *slot* and a run of KV *pages* for its whole
lifetime — placement should follow where that capacity actually is.
Workers already report it: ``/health`` carries a ``decode`` block (free
slots, free pages, prefill backlog — see
``ServingServer.decode_pressure``) plus the pool-wide ``slo_health``
score.  :class:`FleetRouter` turns one snapshot of those signals into a
placement:

- **decode worker** — any worker not dedicated to prefill, scored by
  free slots plus free-page headroom, scaled by ``slo_health`` and
  penalized by queued + in-flight generate work.  Ties break on the
  lower index so placement is deterministic and testable.
- **prefill worker** — only when the topology has dedicated
  ``role="prefill"`` workers (the physical split of
  docs/serving.md §Decode fleet): the least-backlogged prefill worker
  runs the chunked prompt and hands the finished KV pages to the decode
  worker over the :mod:`~bigdl_tpu.serving.fleet.handoff` channel.
  With no prefill-role workers the decode worker prefills locally and
  the second element is None.

The router is pure policy — no I/O, no locks; the pool proxy feeds it
cached ``/health`` snapshots and owns staleness/fallback (a worker with
no decode block, e.g. mid-boot or predict-only, simply scores at zero
pressure-headroom and the proxy's round-robin candidate order still
applies as the fallback)."""

from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = ["FleetRouter"]

# score weights: a free slot is the scarce unit; page headroom breaks
# ties between equally-empty workers; queued work discounts a worker
# that looks free but has admissions racing for it
_W_PAGES = 1.0
_W_BACKLOG = 0.25
_W_PREFILL_BACKLOG = 0.5

# page-headroom weighting by the worker's reported ``page_dtype``
# (docs/quantization.md §Serving memory hierarchy): pages of different
# storage dtypes are NOT interchangeable capacity.  At a fixed HBM
# budget an int8 pool fits ~4x the pages an f32 pool does, so a worker
# reporting the same free-page FRACTION holds ~4x the absolute free
# token capacity — the headroom term counts free pages in
# f32-page-equivalent units rather than scoring the two as equal.
# Workers from before page_dtype existed report nothing and keep the
# f32 weight.
_DTYPE_PAGE_FACTOR = {"float32": 1.0, "bfloat16": 2.0, "int8": 4.0}


def _page_headroom(d: Dict[str, Any]) -> float:
    total_pages = max(float(d.get("total_pages", 0)), 1.0)
    frac = float(d.get("free_pages", 0)) / total_pages
    return frac * _DTYPE_PAGE_FACTOR.get(
        str(d.get("page_dtype", "float32")), 1.0)


class FleetRouter:
    """Pure placement policy: health snapshots in, worker indices out."""

    @staticmethod
    def decode_score(health: Dict[str, Any]) -> float:
        d = health.get("decode") or {}
        slo = float(health.get("slo_health", 1.0))
        free_slots = float(d.get("free_slots", 0))
        backlog = (float(d.get("queued", 0))
                   + float(d.get("generate_inflight", 0)))
        return slo * (free_slots + _W_PAGES * _page_headroom(d)) \
            - _W_BACKLOG * backlog

    @staticmethod
    def prefill_score(health: Dict[str, Any]) -> float:
        d = health.get("decode") or {}
        slo = float(health.get("slo_health", 1.0))
        return slo * (1.0 + _page_headroom(d)) \
            - _W_PREFILL_BACKLOG * float(d.get("prefill_backlog", 0))

    def route(self, healths: Sequence[Dict[str, Any]]
              ) -> Tuple[Optional[int], Optional[int]]:
        """Pick ``(decode_idx, prefill_idx)`` into ``healths``.

        ``prefill_idx`` is None unless the snapshot contains dedicated
        ``role="prefill"`` workers distinct from the chosen decode
        worker; ``(None, None)`` means nothing routable (caller falls
        back to round-robin)."""
        decode_cands: List[int] = []
        prefill_cands: List[int] = []
        for i, h in enumerate(healths):
            if not isinstance(h, dict) or not h.get("alive", True):
                continue
            role = h.get("role", "both")
            if role in ("both", "decode"):
                decode_cands.append(i)
            if role == "prefill":
                prefill_cands.append(i)
        if not decode_cands:
            # a prefill-only fleet can't decode; let the caller fall back
            return (None, None)
        best = max(decode_cands,
                   key=lambda i: (self.decode_score(healths[i]), -i))
        if not prefill_cands:
            return (best, None)
        pre = max(prefill_cands,
                  key=lambda i: (self.prefill_score(healths[i]), -i))
        return (best, pre if pre != best else None)
