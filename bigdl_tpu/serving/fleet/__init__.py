"""Decode fleet — disaggregated prefill/decode serving over the pool.

The decode engine (docs/serving.md §Autoregressive decode) is
single-host: ``enqueue_generate`` binds each request to one worker's
engine, so admission pressure — not step cost — is the wall under load
(DECODE_r01: TTFT p99 3 s at 24 clients while inter-token p99 sits at
5.5 ms).  This package scales generation across the multi-worker
:class:`~bigdl_tpu.serving.pool.ServingPool`:

- :class:`~bigdl_tpu.serving.fleet.router.FleetRouter` — KV-aware
  placement of ``/generate`` over the decode-pressure signals workers
  report in ``/health`` (free slots, free pages, prefill backlog,
  ``slo_health``), replacing round-robin for the generate path.
- :mod:`~bigdl_tpu.serving.fleet.handoff` — the serialized page-transfer
  channel of the physical prefill/decode split: a dedicated prefill
  worker (``role="prefill"``) chunks the prompt, selects the first
  token, and ships the finished KV pages to a decode worker as an exact
  float32 byte image, so the continuation is byte-identical to having
  prefilled locally.
- :class:`~bigdl_tpu.serving.fleet.prefix_cache.PrefixCache` — per-worker
  reuse of KV pages for shared token prefixes (system prompts): the
  common prefix is prefilled once, later requests attach to the cached
  pages copy-on-extend, with hit/miss counters and LRU eviction bounded
  by the engine's page pool.

Everything here preserves the engine's byte-identical-to-
``static_generate`` parity invariant; tests/test_fleet.py proves it for
cached-prefix attach and cross-worker prefill→decode handoff.
"""

from bigdl_tpu.serving.fleet.handoff import (HandoffError, pack_handoff,
                                             unpack_handoff)
from bigdl_tpu.serving.fleet.prefix_cache import PrefixCache
from bigdl_tpu.serving.fleet.router import FleetRouter

__all__ = ["FleetRouter", "HandoffError", "PrefixCache", "pack_handoff",
           "unpack_handoff"]
