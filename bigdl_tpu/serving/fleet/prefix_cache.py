"""Prefix/KV-cache reuse — shared-prompt pages prefilled once per worker.

At fleet scale most prompts share a long common system prefix; paying a
full prefill per request for bytes the worker already computed is pure
waste.  The cache keys *whole finished KV pages* by the token prefix
they cover (the dict lookup hashes the token tuple and verifies equality,
so a hash collision can never alias two different prefixes):

- On completion the engine *donates* the page-aligned prompt-prefix pages
  of a cold request instead of freeing them — ownership moves to the
  cache, so the pages stay out of the engine's free list and page
  accounting stays exact.
- On admission the engine looks up the longest cached page-aligned
  *strict* prefix of the new prompt (strict so the final prefill chunk —
  the one that selects the first token — always runs locally) and maps
  the cached page ids read-only into the slot's page table.  Prefill
  resumes at the attach boundary; the request allocates its own pages
  for everything beyond it (copy-on-extend: shared pages are never
  written — prefill writes at positions >= the attach length, decode at
  positions >= the prompt length).
- Eviction is LRU over entries with zero attached slots only, triggered
  when admission runs out of free pages and bounded by ``max_pages`` at
  insert time — the cache can never starve the live page pool, and never
  frees a page a live slot references.

Byte parity with a cold prefill holds because a position's K/V is a
deterministic causal function of the tokens at or before it and the
engine's prefill math is chunk-boundary- and batch-row-independent (the
padding invariants docs/serving.md §Autoregressive decode pins); the
attach merely substitutes identical bytes for identical work.
tests/test_fleet.py proves it greedy and seeded.

Thread model: ``match/attach/detach/insert/evict`` are called from the
engine thread only; ``stats()`` may be read from any thread.  A small
lock keeps the counters coherent for scrapers.
"""

import threading
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["PrefixCache"]


class _Entry:
    __slots__ = ("key", "pages", "refs", "tick")

    def __init__(self, key: Tuple[int, ...], pages: Sequence[int],
                 tick: int):
        self.key = key
        self.pages = list(pages)
        self.refs = 0           # live slots attached to these pages
        self.tick = tick        # LRU clock (monotonic counter, not time)


class PrefixCache:
    """Token-prefix -> KV-page cache with refcounted LRU eviction."""

    def __init__(self, max_pages: int, page_size: int,
                 page_dtype: str = "float32"):
        if max_pages <= 0:
            raise ValueError(f"max_pages must be positive, got {max_pages}")
        if page_size <= 0:
            raise ValueError(f"page_size must be positive, got {page_size}")
        self.max_pages = int(max_pages)
        self.page_size = int(page_size)
        # the storage dtype every cached page shares (docs/quantization.md
        # §Serving memory hierarchy): a cache holding int8 pages + scales
        # must never accept or serve f32 page ids, and vice versa —
        # attaching a mismatched page would dequantize garbage
        self.page_dtype = str(page_dtype)
        self._entries: Dict[Tuple[int, ...], _Entry] = {}
        self._tick = 0
        self._pages_held = 0
        self._lock = threading.Lock()
        self.stats_counters = {"hits": 0, "misses": 0, "insertions": 0,
                               "rejected_insertions": 0, "evictions": 0,
                               "evicted_pages": 0}

    # -- lookup / refcounting (engine thread) ---------------------------

    def match(self, tokens: Sequence[int]) -> Optional[_Entry]:
        """Longest cached page-aligned STRICT prefix of ``tokens``.

        Does not count a hit or take a reference — admission may still
        push the request back (no free pages); call :meth:`attach` once
        the slot is actually granted, or nothing on push-back."""
        n = len(tokens)
        if n < 2:
            return None
        longest = ((n - 1) // self.page_size) * self.page_size
        with self._lock:
            for length in range(longest, 0, -self.page_size):
                entry = self._entries.get(
                    tuple(int(t) for t in tokens[:length]))
                if entry is not None:
                    return entry
        return None

    def attach(self, entry: _Entry) -> None:
        """A slot now references ``entry``'s pages (counts the hit)."""
        with self._lock:
            self._tick += 1
            entry.refs += 1
            entry.tick = self._tick
            self.stats_counters["hits"] += 1

    def detach(self, entry: _Entry) -> None:
        """The slot released ``entry``'s pages."""
        with self._lock:
            entry.refs -= 1
            if entry.refs < 0:  # pragma: no cover - accounting bug guard
                raise AssertionError(
                    f"prefix-cache refcount underflow for {entry.key!r}")

    def record_miss(self) -> None:
        with self._lock:
            self.stats_counters["misses"] += 1

    # -- population / eviction (engine thread) --------------------------

    def insert(self, tokens: Sequence[int], pages: Sequence[int],
               page_dtype: Optional[str] = None) -> bool:
        """Donate ``pages`` covering exactly ``tokens``.  Returns False
        (caller keeps ownership and frees the pages) when the prefix is
        already cached or the ``max_pages`` budget cannot be made by
        evicting idle entries.  ``page_dtype`` (when given) must match
        the cache's — mixed-dtype page donation is an engine bug, not a
        capacity condition, so it raises instead of returning False."""
        if page_dtype is not None and page_dtype != self.page_dtype:
            raise ValueError(
                f"prefix-cache page dtype mismatch: cache holds "
                f"{self.page_dtype!r} pages, donation is {page_dtype!r}")
        key = tuple(int(t) for t in tokens)
        n = len(pages)
        if n == 0 or len(key) != n * self.page_size:
            return False
        with self._lock:
            if key in self._entries or n > self.max_pages:
                self.stats_counters["rejected_insertions"] += 1
                return False
            over = self._pages_held + n - self.max_pages
            if over > 0 and not self._evict_locked(over):
                self.stats_counters["rejected_insertions"] += 1
                return False
            self._tick += 1
            self._entries[key] = _Entry(key, pages, self._tick)
            self._pages_held += n
            self.stats_counters["insertions"] += 1
            return True

    def evict(self, need_pages: int,
              protect: Optional[_Entry] = None) -> List[int]:
        """Free >= ``need_pages`` pages from idle (refs == 0) entries,
        oldest first; returns the freed page ids (possibly fewer than
        asked when everything else is live).  ``protect`` shields the
        entry the caller is about to attach — it has refs == 0 until the
        admission commits, but its pages are spoken for."""
        with self._lock:
            return self._evict_locked(need_pages, protect) or []

    def _evict_locked(self, need_pages: int,
                      protect: Optional[_Entry] = None) -> List[int]:
        freed: List[int] = []
        while len(freed) < need_pages:
            idle = [e for e in self._entries.values()
                    if e.refs == 0 and e is not protect]
            if not idle:
                break
            victim = min(idle, key=lambda e: e.tick)
            del self._entries[victim.key]
            self._pages_held -= len(victim.pages)
            freed.extend(victim.pages)
            self.stats_counters["evictions"] += 1
            self.stats_counters["evicted_pages"] += len(victim.pages)
        return freed

    # -- introspection (any thread) --------------------------------------

    def stats(self) -> Dict[str, int]:
        with self._lock:
            out = dict(self.stats_counters)
            out["entries"] = len(self._entries)
            out["pages"] = self._pages_held
            out["page_dtype"] = self.page_dtype
            return out

    @property
    def pages_held(self) -> int:
        with self._lock:
            return self._pages_held

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)
