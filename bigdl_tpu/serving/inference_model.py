"""InferenceModel — thread-safe multi-backend predict holder.

Reference analog (unverified — mount empty): ``scala/orca/.../inference/
InferenceModel.scala`` — holds N model replicas in a blocking queue so many
Flink/HTTP threads can predict concurrently; backends BigDL/OpenVINO/TF/
Torch.  TPU-native: ONE jitted program (XLA queues device work; replicas
buy nothing on a single chip — the pure compiled forward is thread-safe by
construction), and batch-size bucketing so arbitrary request sizes hit a
handful of compiled shapes.  Concurrency capacity lives in
``optim.PredictionService``.
"""

import threading

from typing import Any, Callable, Dict, Optional, Sequence

import numpy as np

import jax


def _bucket(n: int, buckets: Sequence[int]) -> int:
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


# One MESH-SHARDED program in flight per process: a sharded predict runs
# collectives across every mesh device, and two host threads launching
# such programs concurrently can interleave their collective rendezvous
# in different orders on different devices — a deadlock.  Unsharded
# predicts don't take this lock (pure jitted forwards are thread-safe by
# construction); sharded ones serialize at launch, which matches the
# per-device program queue a real accelerator runtime enforces anyway.
_MESH_EXEC_LOCK = threading.Lock()




class InferenceModel:
    """Wraps (model, variables) — or any callable — for concurrent serving."""

    def __init__(self, model=None, variables: Optional[Dict] = None,
                 predict_fn: Optional[Callable] = None,
                 batch_buckets: Sequence[int] = (1, 4, 16, 64, 256),
                 decode=None, layout=None,
                 weight_quant: Optional[str] = None):
        """``layout``: serve MODEL-SHARDED (docs/parallelism.md
        §Declarative layouts) — a ``parallelism=`` combo string
        (``"tp:8"``, ``"fsdp:2,tp:4"``) or an already-resolved
        :class:`~bigdl_tpu.parallel.ResolvedLayout`.  The per-model
        layout table places every parameter as a ``NamedSharding`` over
        the named mesh, so a checkpoint too big for one chip serves with
        XLA inserting the collectives; :meth:`warmup`'s closed compile
        set (one program per bucket + the decode engine's cache buckets)
        is unchanged — a mixed-size sweep still runs zero unexpected
        recompiles.  The layout is audited at load: silently replicated
        params export ``parallel.layout.replicated_params`` + a flight
        line.

        ``weight_quant="int8"``: serve int8 weights (docs/quantization.md
        §Serving memory hierarchy).  Layered models (Container / keras
        Model) get the module-swap quantization — Linear/Conv2D leaves
        become int8 twins running the autotuned int8 MXU matmul.  Raw-
        matrix models (Transformer) get weight-only int8 param storage
        dequantized inside the jitted forward, so HBM at rest drops 4x
        and one chip holds a proportionally bigger checkpoint; the
        decode engine's programs inherit the same stored-int8 params.
        Quantization happens AFTER layout placement, so the int8
        tensors keep the layout's shardings."""
        self.layout = None
        if layout is not None:
            from bigdl_tpu.parallel.mesh_policy import (ResolvedLayout,
                                                        mesh_and_layout)

            self.layout = (layout if isinstance(layout, ResolvedLayout)
                           else mesh_and_layout(str(layout)))
        if weight_quant not in (None, "int8"):
            raise ValueError(f"weight_quant {weight_quant!r}: "
                             "None | 'int8'")
        self.weight_quant = weight_quant
        if predict_fn is None:
            if model is None or variables is None:
                raise ValueError("need (model, variables) or predict_fn")

            self._params = variables.get("params", {})
            self._state = variables.get("state", {})
            if self.layout is not None:
                self._params = self.layout.shard_params(model,
                                                        self._params)
            deq = None
            if weight_quant == "int8":
                from bigdl_tpu.nn import quantized as nq
                from bigdl_tpu.nn.module import Container

                if isinstance(model, Container) or nq._is_keras_model(
                        model):
                    # module swap: Linear/Conv2D leaves become int8
                    # twins on the autotuned int8 MXU matmul path
                    model, v = nq.quantize(
                        model, {"params": self._params,
                                "state": self._state})
                    self._params = v.get("params", {})
                    self._state = v.get("state", {})
                else:
                    # raw-matrix models (Transformer): weight-only int8
                    # storage, dequantized inside the jitted forward
                    self._params = nq.quantize_params(self._params)
                    deq = nq.dequantize_params

            def raw(params, state, x):
                if deq is not None:
                    params = deq(params)
                out, _ = model.forward(params, state, x, training=False)
                return out

            self._jit = jax.jit(raw)
            self._custom = None
        else:
            if self.layout is not None:
                raise ValueError("layout= applies to (model, variables) "
                                 "serving, not a custom predict_fn")
            if weight_quant is not None:
                raise ValueError("weight_quant= applies to (model, "
                                 "variables) serving, not a custom "
                                 "predict_fn")
            self._custom = predict_fn
        self.buckets = tuple(sorted(batch_buckets))
        # autoregressive decode path (docs/serving.md §Autoregressive
        # decode): a DecodeConfig attaches the paged-KV continuous
        # decode engine; generate()/generate_stream() and the server's
        # generate requests route through it.  A DecodeConfig with
        # speculative=SpecConfig(...) additionally builds the weight-
        # shared block-sparse draft twin from this model's (already
        # laid-out, already-quantized) params at load time
        # (docs/serving.md §Speculative decoding)
        self.decode_engine = None
        if decode is not None:
            from bigdl_tpu.serving.decode_engine import (DecodeEngine,
                                                         LMAdapter)

            if model is None or getattr(model, "mode", None) != "lm":
                raise ValueError(
                    "decode= needs an LM-mode Transformer (model, "
                    "variables); for translation models use "
                    "Seq2SeqService(continuous=True)")
            # the adapter receives the already-quantized tree under
            # weight_quant="int8" (quantize_params is idempotent) — the
            # engine's traced programs dequantize at each weight read
            adapter = LMAdapter(model, self._params, cap=decode.cap,
                                weight_quant=self.weight_quant)
            self.decode_engine = DecodeEngine(adapter, decode)
        # no lock: the jitted forward is pure and JAX dispatch is
        # thread-safe, so concurrent predicts are safe by construction
        # (the reference needs its replica queue only because its layers
        # carry mutable output/gradInput state).  Concurrency CAPACITY is
        # the caller's concern — see optim.PredictionService.

    @staticmethod
    def load(path: str, model) -> "InferenceModel":
        """Load from the durable model format (``doLoadBigDL`` analog)."""
        from bigdl_tpu.utils.serializer import load_model

        return InferenceModel(model, load_model(path))

    @staticmethod
    def load_tf(path: str, **kwargs) -> "InferenceModel":
        """Serve a frozen TF GraphDef (``doLoadTF``/TFNet analog — no
        libtensorflow: the graph becomes catalog modules via utils.tfio)."""
        from bigdl_tpu.utils.tfio import load_tf_graph

        model, variables = load_tf_graph(path, **kwargs)
        return InferenceModel(model, variables)

    @staticmethod
    def load_caffe(path: str, **kwargs) -> "InferenceModel":
        """Serve a Caffe NetParameter (``doLoadCaffe`` analog); NHWC inputs
        per the utils.caffe import conversion."""
        from bigdl_tpu.utils.caffe import load_caffe

        model, variables = load_caffe(path, **kwargs)
        return InferenceModel(model, variables)

    def predict(self, x: np.ndarray) -> np.ndarray:
        x = np.asarray(x)
        if self._custom is not None:
            return np.asarray(self._custom(x))
        cap = self.buckets[-1]
        if x.shape[0] > cap:
            # chunk instead of running an unpadded tail shape: the set of
            # compiled programs stays CLOSED (one per bucket), so a burst
            # bigger than the largest bucket cannot trigger a fresh XLA
            # compile mid-traffic (the recompile-sentinel guarantee)
            return np.concatenate(
                [self._predict_bucketed(x[i:i + cap])
                 for i in range(0, x.shape[0], cap)], axis=0)
        return self._predict_bucketed(x)

    def _predict_bucketed(self, x: np.ndarray) -> np.ndarray:
        n = x.shape[0]
        b = _bucket(n, self.buckets)
        if n < b:  # pad to the bucket so XLA reuses the compiled program
            pad = np.repeat(x[-1:], b - n, axis=0)
            x = np.concatenate([x, pad], axis=0)
        if self.layout is not None:
            with _MESH_EXEC_LOCK:
                out = self._jit(self._params, self._state, x)
                return np.asarray(out)[:n]
        out = self._jit(self._params, self._state, x)
        return np.asarray(out)[:n]

    # -- autoregressive decode (docs/serving.md §Autoregressive decode) -----
    def generate(self, prompts, max_new_tokens: Optional[int] = None,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 1.0, seeds=None,
                 deadline_s: Optional[float] = None):
        """Generate continuations for ``prompts`` (a list of int token
        sequences) through the continuous decode engine — requests
        share the slot pool with any concurrently streaming traffic.
        Greedy by default; ``temperature/top_k/top_p`` sample with the
        per-request ``seeds`` (defaults to the prompt index).  Returns
        a list of generated-token arrays (EOS included when hit)."""
        import math as _math
        import time as _time

        from bigdl_tpu.serving.decode_engine import DecodeRequest

        if self.decode_engine is None:
            raise ValueError("this InferenceModel has no decode engine; "
                             "construct it with decode=DecodeConfig(...)")
        deadline_t = (_time.time() + deadline_s if deadline_s is not None
                      else _math.inf)
        reqs = []
        for i, p in enumerate(prompts):
            reqs.append(self.decode_engine.submit(DecodeRequest(
                tokens=np.asarray(p, np.int32),
                max_new_tokens=max_new_tokens, temperature=temperature,
                top_k=top_k, top_p=top_p,
                seed=int(seeds[i]) if seeds is not None else i,
                deadline_t=deadline_t)))
        return [r.wait(timeout=300.0).tokens for r in reqs]

    def generate_stream(self, prompt, max_new_tokens: Optional[int] = None,
                        temperature: float = 0.0, top_k: int = 0,
                        top_p: float = 1.0, seed: int = 0,
                        deadline_s: Optional[float] = None):
        """Streaming generate: yields token ids as they decode.  One
        request; keyword args as :meth:`generate`."""
        import math as _math
        import queue as _queue
        import time as _time

        from bigdl_tpu.serving.decode_engine import DecodeRequest

        if self.decode_engine is None:
            raise ValueError("this InferenceModel has no decode engine; "
                             "construct it with decode=DecodeConfig(...)")
        q: _queue.Queue = _queue.Queue()
        done = object()
        req = DecodeRequest(
            tokens=np.asarray(prompt, np.int32),
            max_new_tokens=max_new_tokens, temperature=temperature,
            top_k=top_k, top_p=top_p, seed=seed,
            deadline_t=(_time.time() + deadline_s
                        if deadline_s is not None else _math.inf),
            on_token=lambda rid, tok, idx: q.put(tok),
            on_done=lambda r: q.put(done))
        self.decode_engine.submit(req)
        while True:
            item = q.get()
            if item is done:
                break
            yield item
        if req.error is not None:
            raise req.error

    def warmup(self, sample: np.ndarray) -> "InferenceModel":
        """Compile every bucket's program BEFORE traffic: one predict per
        bucket from ``sample`` (a single example, with or without a batch
        dim), inside an :func:`~bigdl_tpu.obs.attr.expected_compile`
        region so the recompile sentinel stays quiet.  After this, a
        mixed-size request sweep runs with zero XLA compiles."""
        if self._custom is not None:
            return self
        from bigdl_tpu.obs.attr import expected_compile

        row = np.asarray(sample)
        if row.ndim >= 2:
            row = row[:1]
        else:
            row = row[None]
        with expected_compile():
            for b in self.buckets:
                self._predict_bucketed(np.repeat(row, b, axis=0))
        if self.decode_engine is not None:
            self.decode_engine.warmup()
        return self
