"""InferenceModel — thread-safe multi-backend predict holder.

Reference analog (unverified — mount empty): ``scala/orca/.../inference/
InferenceModel.scala`` — holds N model replicas in a blocking queue so many
Flink/HTTP threads can predict concurrently; backends BigDL/OpenVINO/TF/
Torch.  TPU-native: ONE jitted program (XLA queues device work; replicas
buy nothing on a single chip — the pure compiled forward is thread-safe by
construction), and batch-size bucketing so arbitrary request sizes hit a
handful of compiled shapes.  Concurrency capacity lives in
``optim.PredictionService``.
"""

from typing import Any, Callable, Dict, Optional, Sequence

import numpy as np

import jax


def _bucket(n: int, buckets: Sequence[int]) -> int:
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


class InferenceModel:
    """Wraps (model, variables) — or any callable — for concurrent serving."""

    def __init__(self, model=None, variables: Optional[Dict] = None,
                 predict_fn: Optional[Callable] = None,
                 batch_buckets: Sequence[int] = (1, 4, 16, 64, 256)):
        if predict_fn is None:
            if model is None or variables is None:
                raise ValueError("need (model, variables) or predict_fn")

            def raw(params, state, x):
                out, _ = model.forward(params, state, x, training=False)
                return out

            self._jit = jax.jit(raw)
            self._params = variables.get("params", {})
            self._state = variables.get("state", {})
            self._custom = None
        else:
            self._custom = predict_fn
        self.buckets = tuple(sorted(batch_buckets))
        # no lock: the jitted forward is pure and JAX dispatch is
        # thread-safe, so concurrent predicts are safe by construction
        # (the reference needs its replica queue only because its layers
        # carry mutable output/gradInput state).  Concurrency CAPACITY is
        # the caller's concern — see optim.PredictionService.

    @staticmethod
    def load(path: str, model) -> "InferenceModel":
        """Load from the durable model format (``doLoadBigDL`` analog)."""
        from bigdl_tpu.utils.serializer import load_model

        return InferenceModel(model, load_model(path))

    @staticmethod
    def load_tf(path: str, **kwargs) -> "InferenceModel":
        """Serve a frozen TF GraphDef (``doLoadTF``/TFNet analog — no
        libtensorflow: the graph becomes catalog modules via utils.tfio)."""
        from bigdl_tpu.utils.tfio import load_tf_graph

        model, variables = load_tf_graph(path, **kwargs)
        return InferenceModel(model, variables)

    @staticmethod
    def load_caffe(path: str, **kwargs) -> "InferenceModel":
        """Serve a Caffe NetParameter (``doLoadCaffe`` analog); NHWC inputs
        per the utils.caffe import conversion."""
        from bigdl_tpu.utils.caffe import load_caffe

        model, variables = load_caffe(path, **kwargs)
        return InferenceModel(model, variables)

    def predict(self, x: np.ndarray) -> np.ndarray:
        x = np.asarray(x)
        if self._custom is not None:
            return np.asarray(self._custom(x))
        cap = self.buckets[-1]
        if x.shape[0] > cap:
            # chunk instead of running an unpadded tail shape: the set of
            # compiled programs stays CLOSED (one per bucket), so a burst
            # bigger than the largest bucket cannot trigger a fresh XLA
            # compile mid-traffic (the recompile-sentinel guarantee)
            return np.concatenate(
                [self._predict_bucketed(x[i:i + cap])
                 for i in range(0, x.shape[0], cap)], axis=0)
        return self._predict_bucketed(x)

    def _predict_bucketed(self, x: np.ndarray) -> np.ndarray:
        n = x.shape[0]
        b = _bucket(n, self.buckets)
        if n < b:  # pad to the bucket so XLA reuses the compiled program
            pad = np.repeat(x[-1:], b - n, axis=0)
            x = np.concatenate([x, pad], axis=0)
        out = self._jit(self._params, self._state, x)
        return np.asarray(out)[:n]

    def warmup(self, sample: np.ndarray) -> "InferenceModel":
        """Compile every bucket's program BEFORE traffic: one predict per
        bucket from ``sample`` (a single example, with or without a batch
        dim), inside an :func:`~bigdl_tpu.obs.attr.expected_compile`
        region so the recompile sentinel stays quiet.  After this, a
        mixed-size request sweep runs with zero XLA compiles."""
        if self._custom is not None:
            return self
        from bigdl_tpu.obs.attr import expected_compile

        row = np.asarray(sample)
        if row.ndim >= 2:
            row = row[:1]
        else:
            row = row[None]
        with expected_compile():
            for b in self.buckets:
                self._predict_bucketed(np.repeat(row, b, axis=0))
        return self
