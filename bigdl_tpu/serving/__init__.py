"""Serving — streaming/queued inference (SURVEY.md §7 step 9).

Reference analog (unverified — mount empty): Cluster Serving
(``scala/serving``): Redis queue in → Flink streaming job batches requests →
``InferenceModel.doPredict`` → Redis out; plus the python
``InputQueue``/``OutputQueue`` client and the Orca ``InferenceModel``
(a blocking queue of model replicas for concurrent predict).

TPU-native: one process drives the chip; dynamic request batching feeds ONE
jitted forward (padded to bucketed batch sizes so XLA reuses a few compiled
programs); the "model replica queue" concurrency trick is unnecessary —
XLA serializes device execution — but the thread-safe façade remains.
"""

from bigdl_tpu.serving.inference_model import InferenceModel
from bigdl_tpu.serving.server import (DeadlineExceededError,
                                      RequestDroppedError,
                                      ServiceUnavailableError,
                                      ServingConfig, ServingServer)
from bigdl_tpu.serving.client import InputQueue, OutputQueue
from bigdl_tpu.serving.http_frontend import HttpClient, HttpFrontend

from bigdl_tpu.serving.seq2seq import Seq2SeqService
from bigdl_tpu.serving.pool import ServingPool
from bigdl_tpu.serving.decode_engine import (DecodeConfig, DecodeEngine,
                                             DecodeRequest, DecodeResult,
                                             SpecConfig)
from bigdl_tpu.serving.fleet import (FleetRouter, PrefixCache,
                                     pack_handoff, unpack_handoff)

__all__ = [
    "Seq2SeqService", "InferenceModel", "ServingServer", "ServingConfig",
    "InputQueue", "OutputQueue", "HttpFrontend", "HttpClient",
    "ServingPool", "ServiceUnavailableError", "DeadlineExceededError",
    "RequestDroppedError", "DecodeConfig", "DecodeEngine",
    "DecodeRequest", "DecodeResult", "SpecConfig", "FleetRouter",
    "PrefixCache", "pack_handoff", "unpack_handoff"]
