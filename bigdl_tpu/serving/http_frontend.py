"""HTTP frontend for synchronous serving.

Reference analog (unverified — mount empty): the Cluster Serving HTTP
frontend (``scala/serving/.../http/``, akka/netty — SURVEY.md §3.4 row
"Cluster Serving engine"): a sync REST endpoint in front of the
streaming engine.

TPU-native: a stdlib ``ThreadingHTTPServer`` over the in-process
``ServingServer`` queue — requests POST JSON, the engine batches them
onto the chip exactly as queue clients do.  Connections are HTTP/1.1
keep-alive: the pool proxy (and any client that holds its connection)
skips the per-request TCP setup.

    POST /predict   {"instances": [[...], ...],
                     "model": "name"?}           -> {"predictions": [...]}
    GET  /health    -> {"status": "ok", "batches": N, "requests": M,
                        "queue_depth": d, "backlog": b, "p50_ms": ..,
                        "p99_ms": .., "occupancy": .., "models": {...}, ...}
    GET  /models    -> the model registry (multi-tenant serving)
    GET  /metrics   -> Prometheus text exposition (docs/observability.md)

Request lifecycle mapping (docs/serving.md): a per-request deadline rides
in as ``"deadline_s"`` in the payload or an ``X-Deadline-S`` header and is
stamped at admission; backpressure/degradation sheds surface as **429**
with a ``Retry-After`` header (never an open-ended block), a deadline that
expires in the queue is **504**, an oversized body is rejected with
**413** before it is read, an unknown model is **404**, and other engine
errors stay **500**.  The target model rides in as ``"model"`` in the
payload or an ``X-Model`` header (absent: the default tenant).

Observability (docs/observability.md): a caller-supplied ``X-Request-Id``
header (or ``"request_id"`` in the payload) becomes the engine request id,
so one id names the request across the proxy, this frontend, and the
engine's enqueue→batch→predict→publish spans; the id — supplied or
generated — is echoed back as ``X-Request-Id`` on every predict response.
"""

import json
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib import request as _urlreq

import numpy as np

from bigdl_tpu.obs import trace
from bigdl_tpu.obs.export import reply_metrics
from bigdl_tpu.serving.json_http import reply_json
from bigdl_tpu.serving.server import (DeadlineExceededError, MODEL_NAME_RE,
                                      RequestDroppedError,
                                      ServiceUnavailableError, ServingServer)
from bigdl_tpu.utils.log import get_logger

log = get_logger("bigdl_tpu.serving.http")

# caller-supplied request ids are echoed into the X-Request-Id RESPONSE
# header; constrain them to header-safe token characters (a JSON payload
# string could otherwise smuggle CRLF — response splitting).  Checked
# with fullmatch: '$' would still accept a trailing bare newline
REQUEST_ID_RE = re.compile(r"[A-Za-z0-9._:\-]{1,128}")


def _adoptable(parked: dict, tokens, resume: list, kw: dict) -> bool:
    """A parked migration handoff is adoptable iff it is EXACTLY the
    state the resuming stream needs: its tokens are prompt + all-but-
    the-last delivered token, its first_token is the last delivered
    token, and the sampling meta matches — anything else and the
    byte-parity invariant is safer served by re-prefill."""
    try:
        pt = np.asarray(parked["tokens"], np.int32).reshape(-1)
        want = np.concatenate([np.asarray(tokens, np.int32).reshape(-1),
                               np.asarray(resume[:-1], np.int32)])
        return (len(pt) == len(want) and bool(np.array_equal(pt, want))
                and int(parked["first_token"]) == int(resume[-1])
                and float(parked.get("temperature", 0.0))
                == float(kw["temperature"])
                and int(parked.get("top_k", 0)) == int(kw["top_k"])
                and float(parked.get("top_p", 1.0)) == float(kw["top_p"])
                and int(parked.get("seed", 0)) == int(kw["seed"]))
    except Exception:  # noqa: BLE001 — a malformed park is not adoptable
        return False


class _Handler(BaseHTTPRequestHandler):
    server_version = "bigdl-tpu-serving/1"
    # keep-alive: the proxy's per-worker connection reuse (and any
    # persistent client) needs 1.1 — every reply path here sets
    # Content-Length, which 1.1 requires
    protocol_version = "HTTP/1.1"
    # token streams are many tiny writes in the server->client direction;
    # with Nagle on, a chunk can sit in the kernel until the previous
    # one's ACK (http.client already sets TCP_NODELAY on the other side)
    disable_nagle_algorithm = True

    def log_message(self, fmt, *args):  # route to our logger, not stderr
        log.debug(fmt, *args)

    def _json(self, code: int, payload: dict,
              headers: Optional[dict] = None):
        reply_json(self, code, json.dumps(payload).encode(), headers)

    def do_GET(self):
        srv: ServingServer = self.server.serving  # type: ignore[attr-defined]
        if self.path == "/metrics":
            # Prometheus scrape: the server's registry (the process-wide
            # one by default — serving AND training/resilience counters)
            return reply_metrics(self, srv.metrics)
        if self.path == "/models":
            return self._json(200, {"models": srv.models()})
        if self.path != "/health":
            return self._json(404, {"error": f"unknown path {self.path}"})
        stats = dict(srv.stats)
        batches = stats.get("batches", 0)
        # wait-vs-predict tail decomposition + queue pressure: the pool
        # autoscaler's scaling signals, one GET away
        self._json(200, {
            "status": "degraded" if srv.degraded else "ok",
            "degraded": srv.degraded,
            "queue_depth": srv._in.qsize(),
            "backlog": srv.backlog(),
            # decode-fleet routing signals (docs/serving.md §Decode
            # fleet): the worker's role and its engines' slot/page
            # headroom, read by the pool proxy's FleetRouter
            "role": getattr(srv, "role", "both"),
            "decode": srv.decode_pressure(),
            # SLO burn-rate verdicts (docs/observability.md §SLOs & burn
            # rates): the pool autoscaler reads slo_health from here
            "slo_health": srv.slo_health(),
            "slo": srv.slo.snapshot() if srv.slo is not None else None,
            "p50_ms": round(
                srv.metrics.percentile("serving.latency_s", 0.50) * 1e3, 3),
            "p99_ms": round(
                srv.metrics.percentile("serving.latency_s", 0.99) * 1e3, 3),
            "occupancy": round(
                stats.get("requests", 0) / batches
                / max(srv.config.batch_size, 1), 4) if batches else 0.0,
            "models": srv.models(),
            **stats})

    def do_POST(self):
        if self.path == "/generate":
            return self._generate()
        if self.path == "/fleet/prefill":
            return self._fleet_prefill()
        if self.path == "/fleet/import":
            return self._fleet_import()
        if self.path == "/fleet/drain":
            return self._fleet_drain()
        if self.path == "/fleet/evict":
            return self._fleet_evict()
        if self.path == "/recommend":
            return self._recommend()
        if self.path != "/predict":
            return self._json(404, {"error": f"unknown path {self.path}"})
        srv: ServingServer = self.server.serving  # type: ignore[attr-defined]
        try:
            length = int(self.headers.get("Content-Length", "0"))
            if length < 0:
                raise ValueError(length)  # read(-1) would buffer to EOF
        except ValueError:
            self.close_connection = True  # unread body poisons keep-alive
            return self._json(400, {"error": "bad Content-Length"})
        if length > self.server.max_body_bytes:  # type: ignore[attr-defined]
            # reject BEFORE reading: one malformed client must not make
            # the worker buffer an arbitrarily large body.  The unread
            # body makes this connection unusable for a next request —
            # close it instead of letting 1.1 keep-alive misparse
            self.close_connection = True
            return self._json(413, {
                "error": f"request body {length} bytes exceeds limit "
                         f"{self.server.max_body_bytes}"})  # type: ignore[attr-defined]
        deadline_s: Optional[float] = None
        req_id: Optional[str] = None
        model: Optional[str] = None
        try:
            payload = json.loads(self.rfile.read(length) or b"{}")
            instances = np.asarray(payload["instances"], np.float32)
            hdr = self.headers.get("X-Deadline-S")
            raw = payload.get("deadline_s", hdr) \
                if isinstance(payload, dict) else hdr
            if raw is not None:
                deadline_s = float(raw)
            # request correlation: header wins, payload key is the
            # no-custom-headers fallback; absent both, enqueue generates
            req_id = self.headers.get("X-Request-Id") \
                or payload.get("request_id")
            if req_id is not None:
                req_id = str(req_id)
                if not REQUEST_ID_RE.fullmatch(req_id):
                    return self._json(400, {
                        "error": "bad request id: must match "
                                 "[A-Za-z0-9._:-]{1,128}"})
            # multi-tenant routing: payload key wins (it travels with the
            # body through the pool proxy), X-Model header as fallback
            model = payload.get("model") or self.headers.get("X-Model")
            if model is not None:
                model = str(model)
                if not MODEL_NAME_RE.fullmatch(model):
                    return self._json(400, {
                        "error": "bad model name: must match "
                                 "[A-Za-z0-9._-]{1,64}"})
        except (ValueError, KeyError, TypeError, json.JSONDecodeError) as e:
            # TypeError covers valid-JSON non-object bodies ([1,2,3], 42)
            return self._json(400, {"error": f"bad request: {e}"})
        with trace.span("serving/http_request") as sp:
            try:
                rid = srv.enqueue(instances, request_id=req_id,
                                  deadline_s=deadline_s, model=model)
            except KeyError as e:
                # unknown model: a registry miss is the caller naming a
                # tenant this worker does not serve
                return self._json(404, {"error": str(e)})
            except ValueError as e:
                # duplicate in-flight X-Request-Id: usually a client retry
                # racing its first attempt — 409 + Retry-After marks it
                # RETRYABLE (the first attempt resolves within its
                # deadline), never a permanent 400
                return self._json(
                    409, {"error": str(e), "duplicate": True},
                    {"Retry-After": str(srv.config.retry_after_s)})
            except ServiceUnavailableError as e:
                # backpressure / degradation / draining: shed with a retry
                # hint so the client (or the pool proxy) goes elsewhere
                return self._json(429, {"error": str(e)},
                                  {"Retry-After": str(e.retry_after)})
            sp.set_attribute("request_id", rid)
            rid_hdr = {"X-Request-Id": rid}
            try:
                result = srv.query(rid, timeout=self.server.predict_timeout)
            except DeadlineExceededError as e:
                return self._json(504, {"error": str(e), "expired": True},
                                  rid_hdr)
            except RequestDroppedError as e:
                return self._json(503, {"error": str(e)}, rid_hdr)
            except Exception as e:  # noqa: BLE001 — surface as 500, keep serving
                return self._json(500, {"error": str(e)}, rid_hdr)
            self._json(200, {"predictions": np.asarray(result).tolist()},
                       rid_hdr)


    # -- recsys pipeline (docs/recsys.md) -----------------------------------
    def _recommend(self):
        """POST /recommend {"user_id":.., "k":..} — the recommendation
        pipeline surface (docs/recsys.md).  Error mapping mirrors
        /predict: 404 unknown user / no pipeline attached, 409 duplicate
        in-flight id, 429 shed (Retry-After), 504 deadline, 500 other.
        Rides the pool proxy unchanged — any non-/generate POST forwards
        path-verbatim to a worker."""
        pipeline = getattr(self.server, "recsys_pipeline", None)
        if pipeline is None:
            return self._json(404, {
                "error": "no recommendation pipeline attached to this "
                         "frontend (HttpFrontend(recsys_pipeline=...))"})
        try:
            payload = self._read_json_body()
            if payload is None:
                return
            user_id = int(payload["user_id"])
            k = int(payload.get("k", 10))
            if k < 1:
                raise ValueError(f"k must be >= 1, got {k}")
            deadline_s = None
            raw = payload.get("deadline_s",
                              self.headers.get("X-Deadline-S"))
            if raw is not None:
                deadline_s = float(raw)
            req_id = self.headers.get("X-Request-Id") \
                or payload.get("request_id")
            if req_id is not None:
                req_id = str(req_id)
                if not REQUEST_ID_RE.fullmatch(req_id):
                    return self._json(400, {
                        "error": "bad request id: must match "
                                 "[A-Za-z0-9._:-]{1,128}"})
        except (ValueError, KeyError, TypeError,
                json.JSONDecodeError) as e:
            return self._json(400, {"error": f"bad request: {e}"})
        with trace.span("serving/http_recommend") as sp:
            try:
                items = pipeline.recommend(user_id, k=k,
                                           deadline_s=deadline_s,
                                           request_id=req_id)
            except KeyError as e:
                return self._json(404, {"error": str(e)})
            except ValueError as e:
                srv = self.server.serving  # type: ignore[attr-defined]
                return self._json(
                    409, {"error": str(e), "duplicate": True},
                    {"Retry-After": str(srv.config.retry_after_s)})
            except ServiceUnavailableError as e:
                return self._json(429, {"error": str(e)},
                                  {"Retry-After": str(e.retry_after)})
            except (DeadlineExceededError, TimeoutError) as e:
                return self._json(504, {"error": str(e), "expired": True})
            except RequestDroppedError as e:
                return self._json(503, {"error": str(e)})
            except Exception as e:  # noqa: BLE001 — 500, keep serving
                return self._json(500, {"error": str(e)})
            sp.set_attribute("user_id", str(user_id))
            self._json(200, {"items": [{"id": i, "score": s}
                                       for i, s in items]})

    # -- autoregressive decode (docs/serving.md §Autoregressive decode) -----
    def _read_json_body(self):
        try:
            length = int(self.headers.get("Content-Length", "0"))
            if length < 0:
                raise ValueError(length)
        except ValueError:
            # the unread body poisons keep-alive framing — same guard
            # as the predict path
            self.close_connection = True
            self._json(400, {"error": "bad Content-Length"})
            return None
        if length > self.server.max_body_bytes:  # type: ignore[attr-defined]
            self.close_connection = True
            self._json(413, {"error": f"request body {length} bytes "
                             "exceeds limit"})
            return None
        return json.loads(self.rfile.read(length) or b"{}")

    def _chunk(self, data: bytes) -> None:
        self.wfile.write(f"{len(data):X}\r\n".encode() + data + b"\r\n")

    # -- decode fleet (docs/serving.md §Decode fleet) -----------------------
    def _fleet_prefill(self):
        """POST /fleet/prefill — the prefill half of a split generate.
        Body mirrors ``/generate`` (tokens + sampling params); the reply
        is ``pack_handoff`` bytes (application/octet-stream): the prompt
        KV pages, the first token selected during the final prefill
        chunk, and the sampling meta a decode worker resumes from."""
        from bigdl_tpu.serving.fleet import pack_handoff

        srv: ServingServer = self.server.serving  # type: ignore[attr-defined]
        try:
            payload = self._read_json_body()
            if payload is None:
                return
            tokens = np.asarray(payload.get("tokens",
                                            payload.get("prompt")),
                                np.int32)
            req_id = self.headers.get("X-Request-Id") \
                or payload.get("request_id")
            if req_id is not None and \
                    not REQUEST_ID_RE.fullmatch(str(req_id)):
                return self._json(400, {"error": "bad request id"})
            model = payload.get("model") or self.headers.get("X-Model")
            if model is not None and \
                    not MODEL_NAME_RE.fullmatch(str(model)):
                return self._json(400, {"error": "bad model name"})
            kw = dict(
                request_id=req_id, model=model,
                temperature=float(payload.get("temperature", 0.0)),
                top_k=int(payload.get("top_k", 0)),
                top_p=float(payload.get("top_p", 1.0)),
                seed=int(payload.get("seed", 0)))
        except (ValueError, KeyError, TypeError,
                json.JSONDecodeError) as e:
            return self._json(400, {"error": f"bad request: {e}"})
        with trace.span("serving/http_fleet_prefill"):
            try:
                handoff = srv.prefill_handoff(
                    tokens, timeout=self.server.predict_timeout, **kw)  # type: ignore[attr-defined]
            except KeyError as e:
                return self._json(404, {"error": str(e)})
            except TypeError as e:
                return self._json(400, {"error": str(e)})
            except ServiceUnavailableError as e:
                return self._json(429, {"error": str(e)},
                                  {"Retry-After": str(e.retry_after)})
            except ValueError as e:
                return self._json(400, {"error": str(e)})
            except Exception as e:  # noqa: BLE001 — keep serving
                return self._json(500, {"error": str(e)})
        data = pack_handoff(handoff)
        try:
            # chaos seam: a corrupt handoff off the prefill wire — the
            # decode worker's hardened unpack rejects it whole and the
            # stream falls back to a local prefill
            from bigdl_tpu.resilience import faults

            faults.fire("fleet_handoff_corrupt")
        except Exception:  # noqa: BLE001 — any configured action corrupts
            data = b"XXXXXXXX" + data[8:]
        self.send_response(200)
        self.send_header("Content-Type", "application/octet-stream")
        self.send_header("Content-Length", str(len(data)))
        self.send_header("X-Request-Id", str(handoff.get("request_id", "")))
        self.end_headers()
        self.wfile.write(data)

    def _fleet_import(self):
        """POST /fleet/import — park a migrated-in KV handoff (raw
        ``pack_handoff`` bytes) until the pool proxy re-places the
        stream here with ``resume_from``; the resume then adopts the
        parked pages instead of re-prefilling (docs/serving.md §Fleet
        fault tolerance).  A corrupt blob is rejected whole (400) —
        the hardened unpack never partially allocates."""
        from bigdl_tpu.serving.fleet import HandoffError, unpack_handoff

        srv: ServingServer = self.server.serving  # type: ignore[attr-defined]
        try:
            length = int(self.headers.get("Content-Length", "0"))
            if length < 0:
                raise ValueError(length)
        except ValueError:
            self.close_connection = True
            return self._json(400, {"error": "bad Content-Length"})
        if length > self.server.max_body_bytes:  # type: ignore[attr-defined]
            self.close_connection = True
            return self._json(413, {"error": f"handoff of {length} bytes "
                                    "exceeds limit"})
        data = self.rfile.read(length)
        cfg = srv.decode_config()
        try:
            h = unpack_handoff(
                data,
                max_bytes=self.server.max_body_bytes,  # type: ignore[attr-defined]
                max_pages=getattr(cfg, "pages_per_slot", None))
        except HandoffError as e:
            return self._json(400, {"error": str(e)})
        eng_dt = str(getattr(cfg, "kv_dtype", "float32"))
        hd_dt = str(h.get("kv_dtype", "float32"))
        if hd_dt != eng_dt:
            # mixed-dtype pages must never park here (an f32 engine has
            # no scale tables; an int8 engine would quantize-import an
            # f32 image and break handoff parity) — refusing the WHOLE
            # blob makes the drain report the failure and the re-placed
            # stream recover via re-prefill failover instead
            # (docs/quantization.md §Serving memory hierarchy)
            return self._json(400, {
                "error": f"handoff kv_dtype {hd_dt!r} does not match "
                         f"this worker's kv_dtype {eng_dt!r}; refusing "
                         "the page import (re-prefill instead)"})
        rid = srv.park_handoff(h)
        self._json(200, {"parked": rid})

    def _fleet_drain(self):
        """POST /fleet/drain — live-migrate this worker's decode slots
        to ``{"peers": [urls]}``.  ``"evict": false`` leaves the frozen
        slots in place for a later ``/fleet/evict`` (the pool's
        two-phase drain: record the migration map BEFORE the victim's
        streams abort)."""
        srv: ServingServer = self.server.serving  # type: ignore[attr-defined]
        try:
            payload = self._read_json_body()
            if payload is None:
                return
            peers = [str(p) for p in payload.get("peers", [])]
            evict = bool(payload.get("evict", True))
            model = payload.get("model")
        except (ValueError, KeyError, TypeError,
                json.JSONDecodeError) as e:
            return self._json(400, {"error": f"bad request: {e}"})
        try:
            out = srv.drain_decode(peers, model=model, evict=evict)
        except Exception as e:  # noqa: BLE001 — drain is best-effort
            return self._json(500, {"error": str(e)})
        self._json(200, out)

    def _fleet_evict(self):
        """POST /fleet/evict — phase two of the two-phase drain: abort
        the frozen ``{"rids": [...]}`` whose state already shipped."""
        srv: ServingServer = self.server.serving  # type: ignore[attr-defined]
        try:
            payload = self._read_json_body()
            if payload is None:
                return
            rids = [str(r) for r in payload.get("rids", [])]
        except (ValueError, KeyError, TypeError,
                json.JSONDecodeError) as e:
            return self._json(400, {"error": f"bad request: {e}"})
        srv.evict_migrated(rids)
        self._json(200, {"evicted": len(rids)})

    def _remote_prefill(self, url: str, tokens, kw: dict):
        """Ship the prompt to a prefill worker; returns the unpacked
        handoff, or None on any failure (caller prefills locally).

        The deadline is hedged: ``prefill_hedge_s`` (when set, tighter
        than ``predict_timeout``) bounds how long a slow prefill worker
        can stall this stream's TTFT — on breach the request falls back
        to the local prefill path immediately and the breach is counted
        as ``serving.fleet.hedged_prefills``."""
        import socket

        from bigdl_tpu.serving.fleet import unpack_handoff

        srv: ServingServer = self.server.serving  # type: ignore[attr-defined]
        timeout = getattr(self.server, "prefill_hedge_s", None) \
            or self.server.predict_timeout  # type: ignore[attr-defined]
        body = json.dumps({
            "tokens": np.asarray(tokens, np.int32).tolist(),
            "temperature": kw["temperature"], "top_k": kw["top_k"],
            "top_p": kw["top_p"], "seed": kw["seed"],
            "model": kw.get("model"),
            "request_id": kw.get("request_id")}).encode()
        cfg = srv.decode_config(kw.get("model"))
        try:
            req = _urlreq.Request(
                url.rstrip("/") + "/fleet/prefill", data=body,
                headers={"Content-Type": "application/json"})
            with _urlreq.urlopen(req, timeout=timeout) as resp:
                return unpack_handoff(
                    resp.read(),
                    max_pages=getattr(cfg, "pages_per_slot", None))
        except Exception as e:  # noqa: BLE001 — split is best-effort
            reason = getattr(e, "reason", None)
            if isinstance(e, (socket.timeout, TimeoutError)) \
                    or isinstance(reason, (socket.timeout, TimeoutError)):
                srv.metrics.inc("serving.fleet.hedged_prefills")
            log.warning("remote prefill at %s failed (%s); "
                        "prefilling locally", url, e)
            return None

    def _generate(self):
        """POST /generate — token generation over the continuous decode
        engine.  ``{"tokens": [...], "max_new_tokens": n,
        "temperature": t, "top_k": k, "top_p": p, "seed": s,
        "model": name?, "stream": bool}``.

        ``stream=false`` answers one JSON body ``{"tokens": [...]}``.
        ``stream=true`` answers ``Transfer-Encoding: chunked`` NDJSON —
        one ``{"token": id, "index": n}`` line per generated token as
        it decodes, then a final ``{"done": true, "tokens": [...]}``
        line — over the same keep-alive connection (chunked framing is
        what HTTP/1.1 keep-alive needs for a body of unknown length)."""
        srv: ServingServer = self.server.serving  # type: ignore[attr-defined]
        try:
            payload = self._read_json_body()
            if payload is None:
                return
            tokens = np.asarray(payload.get("tokens",
                                            payload.get("prompt")),
                                np.int32)
            stream = bool(payload.get("stream", False))
            req_id = self.headers.get("X-Request-Id") \
                or payload.get("request_id")
            if req_id is not None and \
                    not REQUEST_ID_RE.fullmatch(str(req_id)):
                return self._json(400, {"error": "bad request id"})
            model = payload.get("model") or self.headers.get("X-Model")
            if model is not None and \
                    not MODEL_NAME_RE.fullmatch(str(model)):
                return self._json(400, {"error": "bad model name"})
            hdr = self.headers.get("X-Deadline-S")
            raw = payload.get("deadline_s", hdr)
            deadline_s = float(raw) if raw is not None else None
            resume = payload.get("resume_from")
            if resume is not None:
                if not isinstance(resume, list):
                    return self._json(400, {
                        "error": "resume_from must be a token list"})
                resume = [int(t) for t in resume]
            kw = dict(
                request_id=req_id, deadline_s=deadline_s, model=model,
                max_new_tokens=(int(payload["max_new_tokens"])
                                if "max_new_tokens" in payload else None),
                temperature=float(payload.get("temperature", 0.0)),
                top_k=int(payload.get("top_k", 0)),
                top_p=float(payload.get("top_p", 1.0)),
                seed=int(payload.get("seed", 0)))
        except (ValueError, KeyError, TypeError,
                json.JSONDecodeError) as e:
            return self._json(400, {"error": f"bad request: {e}"})
        # physical prefill/decode split: the pool proxy names a dedicated
        # prefill worker via X-Prefill-Url; run the chunked prefill there
        # and resume decode locally from the shipped KV pages.  Any
        # remote-prefill failure falls back to prefilling locally — the
        # split is an optimization, never an availability dependency
        handoff = None
        prepend: list = []      # tokens the client already holds
        idx_off = 0             # stream indices continue past them
        if resume:
            # mid-stream failover resume (docs/serving.md §Fleet fault
            # tolerance): the pool proxy re-places a stream whose worker
            # died, naming the tokens already delivered.  Two recovery
            # paths, both byte-identical to the no-fault run (sampling
            # keys are counter-based on ABSOLUTE position, so the state
            # after prompt+delivered is the state mid-original-run):
            # adopt a parked migration handoff when one matches, else
            # re-prefill prompt+delivered through the chunked path.
            cfg = srv.decode_config(kw["model"])
            if cfg is None:
                return self._json(404, {
                    "error": "no decode engine to resume on"})
            # the ORIGINAL run's effective token budget (engine
            # admission clamps); the resumed run generates the rest
            eff = min(kw["max_new_tokens"] or cfg.max_new_tokens,
                      cfg.cap - 1, cfg.cap - len(tokens))
            r = len(resume)
            rid_hdr = {"X-Request-Id": str(req_id or "")}
            srv.metrics.inc("serving.fleet.resumes")
            if r >= eff or resume[-1] == cfg.eos_id:
                # the original run would have stopped exactly here:
                # nothing left to generate, answer with what the
                # client already holds
                if not stream:
                    return self._json(200, {"tokens": resume}, rid_hdr)
                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                self.send_header("Transfer-Encoding", "chunked")
                self.send_header("X-Request-Id", str(req_id or ""))
                self.end_headers()
                self._chunk(json.dumps(
                    {"done": True, "tokens": resume}).encode() + b"\n")
                self.wfile.write(b"0\r\n\r\n")
                return
            parked = srv.take_parked(str(req_id)) if req_id else None
            if parked is not None and str(parked.get(
                    "kv_dtype", "float32")) != str(getattr(
                        srv.decode_config(), "kv_dtype", "float32")):
                # a directly-parked handoff in the wrong page dtype
                # (the import gate normally refuses these): byte parity
                # is safer served by re-prefill than a mixed-dtype
                # adoption the engine would reject at submit
                parked = None
            if parked is not None and _adoptable(parked, tokens,
                                                 resume, kw):
                # live migration adoption: the peer shipped the slot's
                # pages here before the victim aborted the stream — no
                # re-prefill, the last delivered token re-emits as the
                # handoff's first_token and decode continues
                handoff = parked
                prepend = resume[:-1]
                idx_off = r - 1
                kw["max_new_tokens"] = eff - (r - 1)
                srv.metrics.inc("serving.fleet.resume_adopted")
            else:
                # re-prefill recovery: prompt + delivered tokens run
                # through chunked prefill (hitting this worker's prefix
                # cache for any shared prefix); generation continues at
                # absolute position prompt+r, exactly where the dead
                # worker stopped
                tokens = np.concatenate(
                    [tokens, np.asarray(resume, np.int32)])
                prepend = list(resume)
                idx_off = r
                kw["max_new_tokens"] = eff - r
                srv.metrics.inc("serving.fleet.resume_reprefill")
        prefill_url = self.headers.get("X-Prefill-Url")
        if prefill_url and not resume:
            handoff = self._remote_prefill(prefill_url, tokens, kw)
        import queue as _queue

        q: "_queue.Queue" = _queue.Queue()
        with trace.span("serving/http_generate") as sp:
            try:
                rid = srv.enqueue_generate(
                    tokens,
                    on_token=(lambda r, t, i: q.put((t, i + idx_off)))
                    if stream else None, handoff=handoff, **kw)
            except KeyError as e:
                return self._json(404, {"error": str(e)})
            except TypeError as e:
                return self._json(400, {"error": str(e)})
            except ValueError as e:
                if "already in flight" in str(e):
                    # duplicate X-Request-Id racing its first attempt —
                    # retryable, like the predict path's 409
                    return self._json(
                        409, {"error": str(e), "duplicate": True},
                        {"Retry-After": str(srv.config.retry_after_s)})
                # submit-time rejection (prompt over the cache cap, ...)
                return self._json(400, {"error": str(e)})
            except ServiceUnavailableError as e:
                return self._json(429, {"error": str(e)},
                                  {"Retry-After": str(e.retry_after)})
            sp.set_attribute("request_id", rid)
            if not stream:
                from bigdl_tpu.serving.decode_engine import \
                    RequestCancelledError

                rid_hdr = {"X-Request-Id": rid}
                try:
                    result = srv.query(
                        rid, timeout=self.server.predict_timeout)
                except DeadlineExceededError as e:
                    return self._json(504, {"error": str(e),
                                            "expired": True}, rid_hdr)
                except RequestCancelledError as e:
                    # slot migrated away mid-request: 503 marks it
                    # retryable — the pool proxy re-runs it elsewhere
                    return self._json(
                        503, {"error": str(e)},
                        dict(rid_hdr, **{"Retry-After": "0.05"}))
                except Exception as e:  # noqa: BLE001
                    return self._json(500, {"error": str(e)}, rid_hdr)
                return self._json(
                    200,
                    {"tokens": prepend + np.asarray(result).tolist()},
                    rid_hdr)
            # streaming: chunked NDJSON, one event per token
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.send_header("Transfer-Encoding", "chunked")
            self.send_header("X-Request-Id", rid)
            self.end_headers()
            deadline = time.time() + self.server.predict_timeout  # type: ignore[attr-defined]

            def _drain_now() -> None:
                # greedy drain into ONE chunk write: at thousands of
                # tokens/s across many handler threads, per-token
                # json.dumps + per-token socket writes would steal the
                # GIL from the decode engine itself
                parts = []
                while True:
                    try:
                        tok, idx = q.get_nowait()
                        parts.append(b'{"token": %d, "index": %d}\n'
                                     % (tok, idx))
                    except _queue.Empty:
                        break
                if parts:
                    self._chunk(b"".join(parts))

            try:
                while True:
                    # the final verdict always lands in the result
                    # table; poll it between token events so an error
                    # (expiry, drop) terminates the stream promptly
                    try:
                        tok, idx = q.get(timeout=0.05)
                        parts = [b'{"token": %d, "index": %d}\n'
                                 % (tok, idx)]
                        while True:
                            try:
                                tok, idx = q.get_nowait()
                                parts.append(
                                    b'{"token": %d, "index": %d}\n'
                                    % (tok, idx))
                            except _queue.Empty:
                                break
                        self._chunk(b"".join(parts))
                        # IDLE timeout, not whole-stream: a healthy
                        # long generation streaming past 30s must not
                        # be cut off mid-flight
                        deadline = (time.time()
                                    + self.server.predict_timeout)  # type: ignore[attr-defined]
                        continue
                    except _queue.Empty:
                        pass
                    with srv._result_cv:
                        done = rid in srv._results
                    if done:
                        break
                    if time.time() > deadline:
                        sp.end()  # error event = completion cue, as above
                        self._chunk(json.dumps(
                            {"error": "generate timed out"}).encode()
                            + b"\n")
                        self.wfile.write(b"0\r\n\r\n")
                        self.close_connection = True
                        return
                # drain any tokens that raced the final verdict
                _drain_now()
                from bigdl_tpu.serving.decode_engine import \
                    RequestCancelledError

                try:
                    result = srv.query(rid, timeout=1.0)
                    final = {"done": True, "tokens":
                             prepend + np.asarray(result).tolist()}
                except DeadlineExceededError as e:
                    final = {"done": True, "error": str(e),
                             "expired": True}
                    partial = getattr(e, "partial_tokens", None)
                    if partial is not None:
                        final["tokens"] = \
                            prepend + np.asarray(partial).tolist()
                except RequestCancelledError:
                    # the slot migrated away (or the client was already
                    # detected gone): abort WITHOUT the chunked
                    # terminator — the pool proxy sees a truncated
                    # stream and fails it over onto the adopting peer;
                    # a proper 0-chunk here would read as a clean,
                    # complete (but token-short) stream
                    self.close_connection = True
                    return
                except Exception as e:  # noqa: BLE001
                    final = {"done": True, "error": str(e)}
                # the done event is the client's cue to move on: export
                # the span BEFORE writing it, or a reader that snapshots
                # the trace right after the stream completes races this
                # thread to the context exit and misses the span
                sp.end()
                self._chunk(json.dumps(final).encode() + b"\n")
                self.wfile.write(b"0\r\n\r\n")
            except (BrokenPipeError, ConnectionResetError):
                # client hung up mid-stream: free the slot + pages NOW
                # instead of decoding to max_new_tokens on a dead socket
                srv.cancel_generate(rid, reason="client_disconnect")
                self.close_connection = True


class HttpFrontend:
    """Serve a ServingServer over HTTP (threaded stdlib server)."""

    def __init__(self, serving: ServingServer, host: str = "127.0.0.1",
                 port: int = 0, predict_timeout: float = 30.0,
                 max_body_bytes: int = 64 * 1024 * 1024,
                 prefill_hedge_s: Optional[float] = None,
                 recsys_pipeline=None):
        self.serving = serving
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.serving = serving  # type: ignore[attr-defined]
        self._httpd.predict_timeout = predict_timeout  # type: ignore[attr-defined]
        self._httpd.max_body_bytes = max_body_bytes  # type: ignore[attr-defined]
        # POST /recommend routes through this pipeline (docs/recsys.md);
        # None keeps the surface 404 until attach_pipeline
        self._httpd.recsys_pipeline = recsys_pipeline  # type: ignore[attr-defined]
        # hedged prefill (docs/serving.md §Fleet fault tolerance): bound
        # the remote-prefill wait tighter than predict_timeout so a
        # straggling prefill worker costs a hedge, not a stalled TTFT
        self._httpd.prefill_hedge_s = prefill_hedge_s  # type: ignore[attr-defined]
        self.host, self.port = self._httpd.server_address[:2]
        self._thread: Optional[threading.Thread] = None

    def attach_pipeline(self, pipeline) -> "HttpFrontend":
        """Attach (or swap) the /recommend pipeline on a live frontend."""
        self._httpd.recsys_pipeline = pipeline  # type: ignore[attr-defined]
        return self

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "HttpFrontend":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        log.info("HTTP frontend listening on %s", self.url)
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)


class HttpClient:
    """Tiny client for the frontend (reference python http client analog).

    ``keep_alive=True`` holds ONE persistent HTTP/1.1 connection (retried
    once on a stale keep-alive socket) — the sustained-load path; not
    thread-safe in that mode, give each client thread its own instance."""

    def __init__(self, url: str, timeout: float = 30.0,
                 keep_alive: bool = False):
        self.url = url.rstrip("/")
        self.timeout = timeout
        self._keep_alive = keep_alive
        self._conn = None

    def predict(self, instances, deadline_s: Optional[float] = None,
                request_id: Optional[str] = None,
                model: Optional[str] = None) -> np.ndarray:
        payload = {"instances": np.asarray(instances).tolist()}
        if deadline_s is not None:
            payload["deadline_s"] = deadline_s
        if model is not None:
            payload["model"] = model
        body = json.dumps(payload).encode()
        headers = {"Content-Type": "application/json"}
        if request_id is not None:
            headers["X-Request-Id"] = request_id
        if self._keep_alive:
            status, data = self._request_keep_alive("POST", "/predict",
                                                    body, headers)
            if status != 200:
                raise RuntimeError(
                    f"predict failed: HTTP {status}: {data[:200]!r}")
            out = json.loads(data)
        else:
            req = _urlreq.Request(self.url + "/predict", data=body,
                                  headers=headers)
            with _urlreq.urlopen(req, timeout=self.timeout) as resp:
                out = json.loads(resp.read())
        return np.asarray(out["predictions"], np.float32)

    def recommend(self, user_id: int, k: int = 10,
                  deadline_s: Optional[float] = None,
                  request_id: Optional[str] = None) -> list:
        """POST /recommend — ranked [(item_id, score), ...] for one user
        through the full feature->recall->ranking pipeline
        (docs/recsys.md)."""
        payload = {"user_id": int(user_id), "k": int(k)}
        if deadline_s is not None:
            payload["deadline_s"] = deadline_s
        body = json.dumps(payload).encode()
        headers = {"Content-Type": "application/json"}
        if request_id is not None:
            headers["X-Request-Id"] = request_id
        if self._keep_alive:
            status, data = self._request_keep_alive("POST", "/recommend",
                                                    body, headers)
            if status != 200:
                raise RuntimeError(
                    f"recommend failed: HTTP {status}: {data[:200]!r}")
            out = json.loads(data)
        else:
            req = _urlreq.Request(self.url + "/recommend", data=body,
                                  headers=headers)
            with _urlreq.urlopen(req, timeout=self.timeout) as resp:
                out = json.loads(resp.read())
        return [(item["id"], item["score"]) for item in out["items"]]

    def generate(self, tokens, max_new_tokens: Optional[int] = None,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 1.0, seed: int = 0,
                 model: Optional[str] = None,
                 deadline_s: Optional[float] = None,
                 request_id: Optional[str] = None, stream: bool = False,
                 resume_from: Optional[list] = None):
        """POST /generate.  ``stream=False`` returns the generated token
        array; ``stream=True`` returns an iterator of NDJSON events —
        ``{"token": id, "index": n}`` per token, then the final
        ``{"done": true, "tokens": [...]}`` — decoded incrementally
        off the chunked response (the wire-framing round-trip the
        decode tests pin).  ``resume_from`` re-places a failed-over
        stream: the tokens already delivered (docs/serving.md §Fleet
        fault tolerance)."""
        payload = {"tokens": np.asarray(tokens, np.int32).tolist(),
                   "temperature": temperature, "top_k": top_k,
                   "top_p": top_p, "seed": seed, "stream": stream}
        if resume_from is not None:
            payload["resume_from"] = [int(t) for t in resume_from]
        if max_new_tokens is not None:
            payload["max_new_tokens"] = max_new_tokens
        if model is not None:
            payload["model"] = model
        if deadline_s is not None:
            payload["deadline_s"] = deadline_s
        body = json.dumps(payload).encode()
        headers = {"Content-Type": "application/json"}
        if request_id is not None:
            headers["X-Request-Id"] = request_id
        if not stream:
            if self._keep_alive:
                status, data = self._request_keep_alive(
                    "POST", "/generate", body, headers)
                if status != 200:
                    raise RuntimeError(
                        f"generate failed: HTTP {status}: {data[:200]!r}")
                out = json.loads(data)
            else:
                # mirror predict(): the keep_alive=False mode stays
                # connection-less (and therefore thread-shareable)
                req = _urlreq.Request(self.url + "/generate", data=body,
                                      headers=headers)
                with _urlreq.urlopen(req, timeout=self.timeout) as resp:
                    out = json.loads(resp.read())
            return np.asarray(out["tokens"], np.int32)
        return self._generate_stream(body, headers)

    def _generate_stream(self, body: bytes, headers: dict):
        import http.client

        host, _, port = self.url.split("//", 1)[1].partition(":")
        conn = http.client.HTTPConnection(host, int(port or 80),
                                          timeout=self.timeout)
        try:
            # a dedicated one-shot connection per stream: ask the server
            # to close it after the final chunk so tearing it down does
            # not reset a kept-alive socket mid-listen
            conn.request("POST", "/generate", body=body,
                         headers=dict(headers, Connection="close"))
            resp = conn.getresponse()
            if resp.status != 200:
                raise RuntimeError(f"generate failed: HTTP {resp.status}: "
                                   f"{resp.read()[:200]!r}")
            # http.client un-chunks transparently; readline yields one
            # NDJSON event per generated token as the server flushes it
            while True:
                line = resp.readline()
                if not line:
                    break
                line = line.strip()
                if not line:
                    continue
                event = json.loads(line)
                yield event
                if event.get("done") or "error" in event:
                    break
        finally:
            conn.close()

    def _request_keep_alive(self, method: str, path: str,
                            body: Optional[bytes], headers: dict):
        import http.client

        for attempt in (0, 1):
            fresh = self._conn is None
            if fresh:
                host, _, port = self.url.split("//", 1)[1].partition(":")
                self._conn = http.client.HTTPConnection(
                    host, int(port or 80), timeout=self.timeout)
            try:
                self._conn.request(method, path, body=body, headers=headers)
                resp = self._conn.getresponse()
                data = resp.read()
            except Exception:
                self.close()
                if fresh or attempt:
                    raise
                continue  # stale keep-alive socket: retry on a fresh one
            if resp.will_close:
                self.close()
            return resp.status, data
        raise RuntimeError("unreachable")

    def close(self) -> None:
        if self._conn is not None:
            try:
                self._conn.close()
            except Exception:  # noqa: BLE001 — already gone
                pass
            self._conn = None

    def models(self) -> dict:
        with _urlreq.urlopen(self.url + "/models",
                             timeout=self.timeout) as resp:
            return json.loads(resp.read())["models"]

    def metrics(self) -> str:
        """One raw Prometheus text scrape of ``GET /metrics``."""
        with _urlreq.urlopen(self.url + "/metrics",
                             timeout=self.timeout) as resp:
            return resp.read().decode()

    def health(self) -> dict:
        with _urlreq.urlopen(self.url + "/health",
                             timeout=self.timeout) as resp:
            return json.loads(resp.read())
