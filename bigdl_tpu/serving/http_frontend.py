"""HTTP frontend for synchronous serving.

Reference analog (unverified — mount empty): the Cluster Serving HTTP
frontend (``scala/serving/.../http/``, akka/netty — SURVEY.md §3.4 row
"Cluster Serving engine"): a sync REST endpoint in front of the
streaming engine.

TPU-native: a stdlib ``ThreadingHTTPServer`` over the in-process
``ServingServer`` queue — requests POST JSON, the dispatcher thread
dynamic-batches them onto the chip exactly as queue clients do.

    POST /predict   {"instances": [[...], ...]}  -> {"predictions": [...]}
    GET  /health    -> {"status": "ok", "batches": N, "requests": M, ...}

Request lifecycle mapping (docs/serving.md): a per-request deadline rides
in as ``"deadline_s"`` in the payload or an ``X-Deadline-S`` header and is
stamped at admission; backpressure/degradation sheds surface as **429**
with a ``Retry-After`` header (never an open-ended block), a deadline that
expires in the queue is **504**, an oversized body is rejected with
**413** before it is read, and other engine errors stay **500**.
"""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib import request as _urlreq

import numpy as np

from bigdl_tpu.serving.json_http import reply_json
from bigdl_tpu.serving.server import (DeadlineExceededError,
                                      RequestDroppedError,
                                      ServiceUnavailableError, ServingServer)
from bigdl_tpu.utils.log import get_logger

log = get_logger("bigdl_tpu.serving.http")


class _Handler(BaseHTTPRequestHandler):
    server_version = "bigdl-tpu-serving/1"

    def log_message(self, fmt, *args):  # route to our logger, not stderr
        log.debug(fmt, *args)

    def _json(self, code: int, payload: dict,
              headers: Optional[dict] = None):
        reply_json(self, code, json.dumps(payload).encode(), headers)

    def do_GET(self):
        if self.path != "/health":
            return self._json(404, {"error": f"unknown path {self.path}"})
        srv: ServingServer = self.server.serving  # type: ignore[attr-defined]
        self._json(200, {"status": "degraded" if srv.degraded else "ok",
                         "degraded": srv.degraded, **srv.stats})

    def do_POST(self):
        if self.path != "/predict":
            return self._json(404, {"error": f"unknown path {self.path}"})
        srv: ServingServer = self.server.serving  # type: ignore[attr-defined]
        try:
            length = int(self.headers.get("Content-Length", "0"))
            if length < 0:
                raise ValueError(length)  # read(-1) would buffer to EOF
        except ValueError:
            return self._json(400, {"error": "bad Content-Length"})
        if length > self.server.max_body_bytes:  # type: ignore[attr-defined]
            # reject BEFORE reading: one malformed client must not make
            # the worker buffer an arbitrarily large body
            return self._json(413, {
                "error": f"request body {length} bytes exceeds limit "
                         f"{self.server.max_body_bytes}"})  # type: ignore[attr-defined]
        deadline_s: Optional[float] = None
        try:
            payload = json.loads(self.rfile.read(length) or b"{}")
            instances = np.asarray(payload["instances"], np.float32)
            hdr = self.headers.get("X-Deadline-S")
            raw = payload.get("deadline_s", hdr) \
                if isinstance(payload, dict) else hdr
            if raw is not None:
                deadline_s = float(raw)
        except (ValueError, KeyError, TypeError, json.JSONDecodeError) as e:
            # TypeError covers valid-JSON non-object bodies ([1,2,3], 42)
            return self._json(400, {"error": f"bad request: {e}"})
        try:
            rid = srv.enqueue(instances, deadline_s=deadline_s)
        except ServiceUnavailableError as e:
            # backpressure / degradation / draining: shed with a retry
            # hint so the client (or the pool proxy) goes elsewhere
            return self._json(429, {"error": str(e)},
                              {"Retry-After": str(e.retry_after)})
        try:
            result = srv.query(rid, timeout=self.server.predict_timeout)
        except DeadlineExceededError as e:
            return self._json(504, {"error": str(e), "expired": True})
        except RequestDroppedError as e:
            return self._json(503, {"error": str(e)})
        except Exception as e:  # noqa: BLE001 — surface as 500, keep serving
            return self._json(500, {"error": str(e)})
        self._json(200, {"predictions": np.asarray(result).tolist()})


class HttpFrontend:
    """Serve a ServingServer over HTTP (threaded stdlib server)."""

    def __init__(self, serving: ServingServer, host: str = "127.0.0.1",
                 port: int = 0, predict_timeout: float = 30.0,
                 max_body_bytes: int = 64 * 1024 * 1024):
        self.serving = serving
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.serving = serving  # type: ignore[attr-defined]
        self._httpd.predict_timeout = predict_timeout  # type: ignore[attr-defined]
        self._httpd.max_body_bytes = max_body_bytes  # type: ignore[attr-defined]
        self.host, self.port = self._httpd.server_address[:2]
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "HttpFrontend":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        log.info("HTTP frontend listening on %s", self.url)
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)


class HttpClient:
    """Tiny client for the frontend (reference python http client analog)."""

    def __init__(self, url: str, timeout: float = 30.0):
        self.url = url.rstrip("/")
        self.timeout = timeout

    def predict(self, instances,
                deadline_s: Optional[float] = None) -> np.ndarray:
        payload = {"instances": np.asarray(instances).tolist()}
        if deadline_s is not None:
            payload["deadline_s"] = deadline_s
        body = json.dumps(payload).encode()
        req = _urlreq.Request(self.url + "/predict", data=body,
                              headers={"Content-Type": "application/json"})
        with _urlreq.urlopen(req, timeout=self.timeout) as resp:
            out = json.loads(resp.read())
        return np.asarray(out["predictions"], np.float32)

    def health(self) -> dict:
        with _urlreq.urlopen(self.url + "/health",
                             timeout=self.timeout) as resp:
            return json.loads(resp.read())
