"""HTTP frontend for synchronous serving.

Reference analog (unverified — mount empty): the Cluster Serving HTTP
frontend (``scala/serving/.../http/``, akka/netty — SURVEY.md §3.4 row
"Cluster Serving engine"): a sync REST endpoint in front of the
streaming engine.

TPU-native: a stdlib ``ThreadingHTTPServer`` over the in-process
``ServingServer`` queue — requests POST JSON, the dispatcher thread
dynamic-batches them onto the chip exactly as queue clients do.

    POST /predict   {"instances": [[...], ...]}  -> {"predictions": [...]}
    GET  /health    -> {"status": "ok", "batches": N, "requests": M}
"""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib import request as _urlreq

import numpy as np

from bigdl_tpu.serving.server import ServingServer
from bigdl_tpu.utils.log import get_logger

log = get_logger("bigdl_tpu.serving.http")


class _Handler(BaseHTTPRequestHandler):
    server_version = "bigdl-tpu-serving/1"

    def log_message(self, fmt, *args):  # route to our logger, not stderr
        log.debug(fmt, *args)

    def _json(self, code: int, payload: dict):
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        if self.path != "/health":
            return self._json(404, {"error": f"unknown path {self.path}"})
        srv: ServingServer = self.server.serving  # type: ignore[attr-defined]
        self._json(200, {"status": "ok", **srv.stats})

    def do_POST(self):
        if self.path != "/predict":
            return self._json(404, {"error": f"unknown path {self.path}"})
        try:
            length = int(self.headers.get("Content-Length", "0"))
            payload = json.loads(self.rfile.read(length) or b"{}")
            instances = np.asarray(payload["instances"], np.float32)
        except (ValueError, KeyError, TypeError, json.JSONDecodeError) as e:
            # TypeError covers valid-JSON non-object bodies ([1,2,3], 42)
            return self._json(400, {"error": f"bad request: {e}"})
        srv: ServingServer = self.server.serving  # type: ignore[attr-defined]
        try:
            rid = srv.enqueue(instances)
            result = srv.query(rid, timeout=self.server.predict_timeout)
        except Exception as e:  # noqa: BLE001 — surface as 500, keep serving
            return self._json(500, {"error": str(e)})
        self._json(200, {"predictions": np.asarray(result).tolist()})


class HttpFrontend:
    """Serve a ServingServer over HTTP (threaded stdlib server)."""

    def __init__(self, serving: ServingServer, host: str = "127.0.0.1",
                 port: int = 0, predict_timeout: float = 30.0):
        self.serving = serving
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.serving = serving  # type: ignore[attr-defined]
        self._httpd.predict_timeout = predict_timeout  # type: ignore[attr-defined]
        self.host, self.port = self._httpd.server_address[:2]
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "HttpFrontend":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        log.info("HTTP frontend listening on %s", self.url)
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)


class HttpClient:
    """Tiny client for the frontend (reference python http client analog)."""

    def __init__(self, url: str, timeout: float = 30.0):
        self.url = url.rstrip("/")
        self.timeout = timeout

    def predict(self, instances) -> np.ndarray:
        body = json.dumps(
            {"instances": np.asarray(instances).tolist()}).encode()
        req = _urlreq.Request(self.url + "/predict", data=body,
                              headers={"Content-Type": "application/json"})
        with _urlreq.urlopen(req, timeout=self.timeout) as resp:
            out = json.loads(resp.read())
        return np.asarray(out["predictions"], np.float32)

    def health(self) -> dict:
        with _urlreq.urlopen(self.url + "/health",
                             timeout=self.timeout) as resp:
            return json.loads(resp.read())
