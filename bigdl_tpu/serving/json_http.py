"""Shared JSON-over-HTTP micro server.

One implementation of the threaded JSON endpoint scaffolding used by the
serving frontends (Cluster-Serving HTTP frontend, Friesian recsys surface)
so error mapping, socket lifecycle, and threading cannot drift between
copies.  Routes are ``{"/path": fn(request_dict) -> response_dict}``;
handler exceptions map to 400 (KeyError — missing/unknown key) or 500,
and the server always stays up.
"""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional


def reply_json(handler: BaseHTTPRequestHandler, code: int, body: bytes,
               headers: Optional[Dict[str, str]] = None) -> None:
    """Write one JSON response, tolerating a client that already hung up
    (its own timeout) — the abandoned-request case must not traceback.
    Shared by every serving HTTP surface (frontend, pool proxy, this
    scaffolding) so the write path cannot drift between copies."""
    try:
        handler.send_response(code)
        handler.send_header("Content-Type", "application/json")
        handler.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            handler.send_header(k, v)
        handler.end_headers()
        handler.wfile.write(body)
    except (BrokenPipeError, ConnectionResetError):
        pass


class JsonHTTPServer:
    def __init__(self, routes: Dict[str, Callable[[dict], dict]],
                 host: str = "127.0.0.1", port: int = 0,
                 max_body_bytes: int = 64 * 1024 * 1024):
        server_routes = dict(routes)
        body_limit = max_body_bytes

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _json(self, code, payload):
                reply_json(self, code, json.dumps(payload).encode())

            def do_POST(self):
                try:
                    fn = server_routes.get(self.path)
                    if fn is None:
                        self._json(404, {"error": f"no route {self.path}"})
                        return
                    try:
                        n = int(self.headers.get("Content-Length", 0))
                        if n < 0:       # read(-1) would buffer to EOF
                            raise ValueError(n)
                    except ValueError:
                        self._json(400, {"error": "bad Content-Length"})
                        return
                    if n > body_limit:
                        # bound BEFORE reading: a malformed client must
                        # not make this process buffer an arbitrary body
                        self._json(413, {"error": f"request body {n} "
                                         f"bytes exceeds {body_limit}"})
                        return
                    req = json.loads(self.rfile.read(n) or b"{}")
                    self._json(200, fn(req))
                except KeyError as e:
                    self._json(400, {"error": f"missing/unknown key: {e}"})
                except Exception as e:  # noqa: BLE001 — service stays up
                    self._json(500, {"error": str(e)})

        self._srv = ThreadingHTTPServer((host, port), Handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        h, p = self._srv.server_address
        return f"http://{h}:{p}"

    def start(self) -> "JsonHTTPServer":
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()  # release the listening socket
        if self._thread:
            self._thread.join(timeout=5)
